"""Failure detection for the PS mode (SURVEY.md §5.3).

The reference has no failure handling at all: a dead worker in sync mode
deadlocks the BSP barrier forever (``src/main.cc:67-78`` waits for
exactly ``NumWorkers()`` pushes).  These tests pin the framework's
answer: client-side op timeouts that raise a *named* straggler error,
and a stats probe that stays answerable while the barrier is wedged.
"""

import time

import numpy as np
import pytest

from distlr_tpu.ps import KVWorker, PSTimeoutError, ServerGroup


def _wait_pending_zero(group, *, deadline_s: float = 5.0) -> int:
    """Poll server 0 until its deferred-push count drops to 0 (the
    disconnect rollback runs on the server's reader thread, which races
    a freshly-connected stats probe)."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        pending = group.health()[0]["pending_sync_pushes"]
        if pending == 0:
            return 0
        time.sleep(0.02)
    return pending


@pytest.fixture()
def sync_group_of_two():
    """Sync server expecting 2 workers — one never shows up."""
    with ServerGroup(1, 2, dim=8, sync=True, learning_rate=0.5) as group:
        yield group


class TestStragglerTimeout:
    def test_sync_push_times_out_with_named_straggler_error(self, sync_group_of_two):
        with KVWorker(sync_group_of_two.hosts, 8, client_id=0, timeout_ms=300) as kv:
            kv.push(np.zeros(8, np.float32))  # first push = init, replies at once
            t0 = time.monotonic()
            with pytest.raises(PSTimeoutError, match="straggler|BSP barrier"):
                kv.push(np.ones(8, np.float32))  # deferred: needs 2 workers
            assert time.monotonic() - t0 < 5.0  # timed out, not deadlocked

    def test_barrier_times_out_when_peer_missing(self, sync_group_of_two):
        with KVWorker(sync_group_of_two.hosts, 8, client_id=0, timeout_ms=300) as kv:
            with pytest.raises(PSTimeoutError):
                kv.barrier()

    def test_zero_timeout_means_blocking(self, sync_group_of_two):
        # timeout_ms=0 must not set a timeout: a pull (never deferred)
        # still completes after an arbitrary client-side pause.
        with KVWorker(sync_group_of_two.hosts, 8, client_id=0, timeout_ms=0) as kv:
            kv.push(np.zeros(8, np.float32))
            time.sleep(0.4)
            assert kv.pull().shape == (8,)


class TestStatsProbe:
    def test_stats_reflect_progress_and_survive_wedged_barrier(self):
        with ServerGroup(2, 2, dim=10, sync=True) as group:
            with KVWorker(group.hosts, 10, client_id=0, timeout_ms=500) as kv:
                kv.push(np.zeros(10, np.float32))  # init both servers
                kv.pull()
                with pytest.raises(PSTimeoutError):
                    kv.push(np.ones(10, np.float32))  # wedges the barrier
                # probe on a FRESH connection while the wedged push is
                # still pending (the timed-out client is alive, just
                # poisoned client-side)
                health = group.health(timeout_ms=1000)
                assert len(health) == 2
                for h, dim in zip(health, (5, 5)):
                    assert h["dim"] == dim
                    assert h["initialized"] == 1
                    assert h["pending_sync_pushes"] == 1  # the wedged push
                    assert h["total_pushes"] == 2
                    assert h["total_pulls"] == 1
            # once the wedged client disconnects, its deferred push is
            # rolled back (see TestWorkerRestartRecovery)
            assert _wait_pending_zero(group) == 0

    def test_alive_tracks_processes(self):
        group = ServerGroup(1, 1, dim=4, sync=False).start()
        assert group.alive() == [True]
        group.stop()
        assert group.alive() == []


class TestWorkerRestartRecovery:
    def test_reconnected_worker_is_not_double_counted(self, sync_group_of_two):
        """A worker that times out, reconnects, and re-pushes must count
        once: the server rolls the dead connection's deferred push out of
        the merge buffer (no rollback -> the barrier would release early
        with a duplicated gradient)."""
        hosts = sync_group_of_two.hosts
        with KVWorker(hosts, 8, client_id=0, timeout_ms=300) as kv:
            kv.push(np.zeros(8, np.float32))  # init
            with pytest.raises(PSTimeoutError):
                kv.push(np.ones(8, np.float32))  # deferred, then timeout
        # old connection closed -> server must have rolled its push back
        assert _wait_pending_zero(sync_group_of_two) == 0

        # restart: reconnect and train with BOTH workers present
        kv0 = KVWorker(hosts, 8, client_id=0, timeout_ms=3000)
        kv1 = KVWorker(hosts, 8, client_id=1, timeout_ms=3000)
        import threading

        g0 = np.full(8, 1.0, np.float32)
        g1 = np.full(8, 3.0, np.float32)
        t = threading.Thread(target=lambda: kv1.push(g1))
        t.start()
        kv0.push(g0)  # releases once both arrive
        t.join()
        w = kv0.pull()
        kv0.close()
        kv1.close()
        # exactly one mean update: -lr * (1+3)/2 = -0.5 * 2 = -1
        np.testing.assert_allclose(w, -1.0 * np.ones(8), rtol=1e-6)

    def test_poisoned_connection_fails_fast_after_timeout(self, sync_group_of_two):
        with KVWorker(sync_group_of_two.hosts, 8, client_id=0, timeout_ms=300) as kv:
            kv.push(np.zeros(8, np.float32))
            with pytest.raises(PSTimeoutError):
                kv.push(np.ones(8, np.float32))
            with pytest.raises(IOError, match="poisoned"):
                kv.pull()

    def test_reconnect_recovers_poisoned_connection_in_place(self, sync_group_of_two):
        """The poisoned-connection dead end, fixed: reconnect() rebuilds
        the native handle on the SAME object (dim/timeout/group-mode
        preserved) and the next op completes — callers running their own
        retry loop no longer have to recreate the KVWorker."""
        with KVWorker(sync_group_of_two.hosts, 8, client_id=0, timeout_ms=300) as kv:
            kv.push(np.zeros(8, np.float32))
            with pytest.raises(PSTimeoutError):
                kv.push(np.ones(8, np.float32))  # wedged barrier -> poisoned
            with pytest.raises(IOError, match="poisoned"):
                kv.pull()
            kv.reconnect()
            # a pull (never deferred) completes on the rebuilt handle
            np.testing.assert_allclose(kv.pull(), np.zeros(8), rtol=1e-6)
            # the receive timeout survived the rebuild: a second wedged
            # push still times out fast instead of blocking forever
            t0 = time.monotonic()
            with pytest.raises(PSTimeoutError):
                kv.push(np.ones(8, np.float32))
            assert time.monotonic() - t0 < 5.0


class TestAsyncUnaffected:
    def test_async_single_worker_never_needs_peers(self):
        with ServerGroup(1, 4, dim=6, sync=False) as group:
            with KVWorker(group.hosts, 6, client_id=0, timeout_ms=1000) as kv:
                kv.push(np.zeros(6, np.float32))  # init
                kv.push(np.full(6, 2.0, np.float32))  # applied immediately
                w = kv.pull()
                np.testing.assert_allclose(w, -0.2 * 2.0 * np.ones(6), rtol=1e-6)


class TestAsyncWorkerRestart:
    """run_ps_workers(max_restarts=N): async workers are rebuilt in place
    after a failure and rejoin the group (Hogwild tolerates arbitrary
    rejoin; the server's disconnect rollback cleared any partial state).
    The reference's only outcome for ANY worker failure is a hang."""

    def test_failed_async_worker_restarts_and_run_completes(self, tmp_path, monkeypatch):
        from distlr_tpu.config import Config
        from distlr_tpu.data.synthetic import write_synthetic_shards
        from distlr_tpu.train import ps_trainer
        from distlr_tpu.train.ps_trainer import PSWorker, run_ps_local

        d = str(tmp_path / "data")
        write_synthetic_shards(d, 1200, 16, num_parts=2, seed=9, sparsity=0.0)

        # Rank 1's first load blows up (simulating a worker crash at
        # startup); the restarted instance succeeds.
        real_load = PSWorker._load_train_iter
        failures = {"left": 1}

        def flaky_load(self):
            if self.rank == 1 and failures["left"] > 0:
                failures["left"] -= 1
                raise RuntimeError("injected worker crash")
            return real_load(self)

        monkeypatch.setattr(PSWorker, "_load_train_iter", flaky_load)
        cfg = Config(
            data_dir=d, num_feature_dim=16, num_workers=2, num_servers=1,
            num_iteration=10, learning_rate=0.2, l2_c=0.0, batch_size=100,
            test_interval=0, sync_mode=False,
        )
        results = run_ps_local(cfg, save=False, max_restarts=2)
        assert failures["left"] == 0  # the injected crash actually fired
        assert all(r is not None for r in results)

    def test_async_failure_without_restarts_still_raises(self, tmp_path, monkeypatch):
        from distlr_tpu.config import Config
        from distlr_tpu.data.synthetic import write_synthetic_shards
        from distlr_tpu.train.ps_trainer import PSWorker, run_ps_local

        d = str(tmp_path / "data")
        write_synthetic_shards(d, 600, 16, num_parts=2, seed=9, sparsity=0.0)
        monkeypatch.setattr(
            PSWorker, "_load_train_iter",
            lambda self: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        cfg = Config(
            data_dir=d, num_feature_dim=16, num_workers=2, num_servers=1,
            num_iteration=3, sync_mode=False, test_interval=0, batch_size=100,
        )
        with pytest.raises(RuntimeError):
            run_ps_local(cfg, save=False)

    def test_sync_mode_never_restarts_in_place(self, tmp_path, monkeypatch):
        """BSP rounds are counted per worker: sync recovery is job-level
        checkpoint+resume, so max_restarts must not mask a sync failure."""
        from distlr_tpu.config import Config
        from distlr_tpu.data.synthetic import write_synthetic_shards
        from distlr_tpu.train.ps_trainer import PSWorker, run_ps_local

        d = str(tmp_path / "data")
        write_synthetic_shards(d, 600, 16, num_parts=2, seed=9, sparsity=0.0)
        calls = {"n": 0}
        def always_fail(self):
            calls["n"] += 1
            raise RuntimeError("boom")
        monkeypatch.setattr(PSWorker, "_load_train_iter", always_fail)
        cfg = Config(
            data_dir=d, num_feature_dim=16, num_workers=2, num_servers=1,
            num_iteration=3, sync_mode=True, test_interval=0, batch_size=-1,
        )
        with pytest.raises(RuntimeError):
            run_ps_local(cfg, save=False, max_restarts=5)
        assert calls["n"] <= 2  # one attempt per rank, no retries


class TestMidTrainingRestart:
    def test_async_worker_crash_mid_training_recovers(self, tmp_path, monkeypatch):
        """The advertised case: a worker dies AFTER the startup barrier
        (mid-epoch), restarts, re-sends its idempotent init, re-votes the
        released generation-0 barrier (instant), and rejoins — while
        rank 0's exit vote (generation 1) can never pair with it."""
        from distlr_tpu.config import Config
        from distlr_tpu.data.synthetic import write_synthetic_shards
        from distlr_tpu.train import ps_trainer
        from distlr_tpu.train.ps_trainer import run_ps_local

        d = str(tmp_path / "data")
        write_synthetic_shards(d, 1200, 16, num_parts=2, seed=9, sparsity=0.0)

        # inject at the dense hot path (the numpy fast-path grad — tiny
        # D=16 steps route there, not through _place)
        real_grad = ps_trainer._np_dense_grad
        state = {"calls": 0, "crashed": False}

        def flaky_grad(*args, **kw):
            # rank-agnostic but only one crash: trip after a few batches
            state["calls"] += 1
            if not state["crashed"] and state["calls"] == 5:
                state["crashed"] = True
                raise RuntimeError("injected mid-training crash")
            return real_grad(*args, **kw)

        monkeypatch.setattr(ps_trainer, "_np_dense_grad", flaky_grad)
        cfg = Config(
            data_dir=d, num_feature_dim=16, num_workers=2, num_servers=2,
            num_iteration=8, learning_rate=0.2, l2_c=0.0, batch_size=100,
            test_interval=0, sync_mode=False,
        )
        results = run_ps_local(cfg, save=False, max_restarts=2)
        assert state["crashed"]
        assert all(r is not None for r in results)
        # weights stayed sane (a re-applied init-as-gradient would shift
        # every weight by -lr*[0,1) — catch gross corruption)
        assert np.isfinite(results[0]).all()


class TestInitIdempotence:
    def test_force_init_overwrites_surviving_group(self):
        """Checkpoint resume against servers that survived a worker-job
        crash: the restored weights must REPLACE the stale live ones
        (plain idempotent init would no-op and silently resume wrong)."""
        from distlr_tpu.ps import KVWorker, ServerGroup

        with ServerGroup(1, 1, dim=4, learning_rate=1.0, sync=False) as sg:
            with KVWorker(sg.hosts, 4, timeout_ms=20_000) as kv:
                kv.wait(kv.push_init(np.arange(4, dtype=np.float32)))
                kv.wait(kv.push(np.ones(4, np.float32)))  # live training drift
                restored = np.full(4, 7.0, np.float32)
                kv.wait(kv.push_init(restored, force=True))
                np.testing.assert_allclose(kv.pull(), restored)
                kv.shutdown_servers()

    def test_barrier_id_range_checked(self):
        from distlr_tpu.ps import KVWorker, ServerGroup

        with ServerGroup(1, 1, dim=2, sync=False) as sg:
            with KVWorker(sg.hosts, 2, timeout_ms=20_000) as kv:
                with pytest.raises(ValueError, match="uint16"):
                    kv.barrier(1 << 16)
                kv.shutdown_servers()

    def test_push_init_noops_after_initialization(self):
        from distlr_tpu.ps import KVWorker, ServerGroup

        with ServerGroup(1, 1, dim=4, learning_rate=1.0, sync=False) as sg:
            with KVWorker(sg.hosts, 4, timeout_ms=20_000) as kv:
                kv.wait(kv.push_init(np.arange(4, dtype=np.float32)))
                # second init (a restarted rank 0) must not touch weights
                kv.wait(kv.push_init(np.full(4, 99.0, np.float32)))
                np.testing.assert_allclose(kv.pull(), np.arange(4))
                kv.shutdown_servers()

    def test_barrier_revote_same_client_never_double_counts(self):
        """One vote per CLIENT per generation, not per connection: a
        worker that times out and re-votes (reconnect path) must not
        hold two live votes.  Nothing orders the re-vote after the old
        connection's DropConnection rollback (separate server reader
        threads), so without client_id dedup the exit barrier could
        release with a peer absent — and rank 0 would shut the servers
        down under a still-training worker (found by the chaos soak)."""
        import threading

        with ServerGroup(1, 2, dim=8, sync=False) as g:
            kv1 = KVWorker(g.hosts, 8, client_id=0, timeout_ms=400)
            with pytest.raises(PSTimeoutError):
                kv1.barrier(3)  # 1 of 2 votes: wedged
            # same client re-votes on a SECOND live connection (the
            # reconnect race shape: old vote not yet rolled back)
            kv2 = KVWorker(g.hosts, 8, client_id=0, timeout_ms=400)
            with pytest.raises(PSTimeoutError):
                kv2.barrier(3)  # must still be 1 effective vote
            # the real second worker arrives: NOW it releases, and the
            # rank-0 reply routes to the replacement (live) connection
            kv3 = KVWorker(g.hosts, 8, client_id=1, timeout_ms=5000)
            t = threading.Thread(target=kv3.barrier, args=(3,))
            t.start()
            t.join(timeout=5)
            assert not t.is_alive(), "barrier never released"
            for kv in (kv1, kv2, kv3):
                kv.close()

    def test_released_barrier_generation_passes_late_votes(self):
        from distlr_tpu.ps import KVWorker, ServerGroup

        with ServerGroup(1, 1, dim=2, sync=False) as sg:
            with KVWorker(sg.hosts, 2, timeout_ms=20_000) as kv:
                kv.barrier(0)   # 1 worker: releases immediately
                kv.barrier(0)   # late re-vote: must return, not hang
                kv.barrier(1)   # next generation independent
                kv.shutdown_servers()


class TestSurvivingGroupResume:
    """Job-level resume against a server group that SURVIVED the worker
    crash (ADVICE r1): the group already released the crashed run's
    startup barrier generation, so the resumed run must rendezvous on a
    FRESH generation pair (sidecar attempt counter, bumped once per
    resume by the launcher) — otherwise peers sail through barrier(0)
    and pull stale crash-time weights before rank 0's forced init."""

    def test_resume_against_surviving_group(self, tmp_path, monkeypatch):
        import json
        import os
        import shutil

        from distlr_tpu.config import Config
        from distlr_tpu.data.synthetic import write_synthetic_shards
        from distlr_tpu.train.ps_trainer import (
            PSWorker, ps_param_dim, run_ps_local, run_ps_workers,
        )

        d = str(tmp_path / "data")
        write_synthetic_shards(d, 600, 16, num_parts=2, seed=9, sparsity=0.0)
        ck = str(tmp_path / "ck")
        cfg = Config(
            data_dir=d, num_feature_dim=16, num_workers=2, num_servers=2,
            num_iteration=4, learning_rate=0.5, l2_c=0.0, batch_size=-1,
            test_interval=0, sync_mode=True, checkpoint_dir=ck,
            checkpoint_interval=2, ps_timeout_ms=4000,
        )

        # Rank 0 dies right after writing the epoch-2 checkpoint; rank 1
        # then times out on the next BSP round.  No on_error: servers live.
        real_ckpt = PSWorker._checkpoint
        state = {"crashed": False}

        def crashing_ckpt(self, ckpt, epoch):
            real_ckpt(self, ckpt, epoch)
            if epoch == 2 and not state["crashed"]:
                state["crashed"] = True
                raise RuntimeError("injected crash after checkpoint")

        monkeypatch.setattr(PSWorker, "_checkpoint", crashing_ckpt)
        group = ServerGroup(2, 2, ps_param_dim(cfg), learning_rate=0.5, sync=True)
        with group:
            with pytest.raises(Exception):
                run_ps_workers(cfg, group.hosts, range(2), save=False)
            assert state["crashed"]
            with open(os.path.join(ck, "ps_latest.json")) as f:
                sc = json.load(f)
            assert sc == {"epoch": 2, "attempt": 0}

            # Deterministic oracle: the same resume on a FRESH group from
            # a copy of the checkpoint (sync full-batch is deterministic).
            ck2 = str(tmp_path / "ck2")
            shutil.copytree(ck, ck2)

            resumed = run_ps_workers(
                cfg, group.hosts, range(2), save=False, resume=True,
            )
        with open(os.path.join(ck, "ps_latest.json")) as f:
            sc = json.load(f)
        assert sc["attempt"] == 1, "resume must advance the barrier epoch"
        assert sc["epoch"] == 4

        ref = run_ps_local(
            cfg.replace(checkpoint_dir=ck2), save=False, resume=True,
        )
        np.testing.assert_allclose(resumed[0], ref[0], rtol=1e-5, atol=1e-6)

    def test_bump_resume_attempt_preserves_epoch_and_creates_missing_sidecar(self, tmp_path):
        import json
        import os

        from distlr_tpu.config import Config
        from distlr_tpu.train.ps_trainer import bump_resume_attempt

        cfg = Config(checkpoint_dir=str(tmp_path / "ck"), num_feature_dim=4)
        # No sidecar (crash predated the first checkpoint): the resume must
        # still get a fresh barrier generation, so the sidecar is CREATED
        # at epoch 0 (ADVICE r2 — a no-op here reused released barrier 0).
        bump_resume_attempt(cfg)
        sidecar = os.path.join(cfg.checkpoint_dir, "ps_latest.json")
        with open(sidecar) as f:
            assert json.load(f) == {"epoch": 0, "attempt": 1}

        with open(sidecar, "w") as f:
            json.dump({"epoch": 6}, f)  # legacy sidecar without attempt
        bump_resume_attempt(cfg)
        bump_resume_attempt(cfg)
        with open(sidecar) as f:
            assert json.load(f) == {"epoch": 6, "attempt": 2}

    def test_resume_before_first_checkpoint_reinitializes(self, tmp_path, monkeypatch):
        """Workers crash BEFORE any checkpoint exists; the surviving server
        group holds stale crash-time weights and has already released
        barrier generation 0.  The resume must (a) rendezvous on a fresh
        generation and (b) force a fresh epoch-0 init over the stale
        weights — equaling a from-scratch run on a fresh group."""
        import json
        import os

        from distlr_tpu.config import Config
        from distlr_tpu.data.synthetic import write_synthetic_shards
        from distlr_tpu.train.ps_trainer import (
            PSWorker, ps_param_dim, run_ps_local, run_ps_workers,
        )

        d = str(tmp_path / "data")
        write_synthetic_shards(d, 600, 16, num_parts=2, seed=9, sparsity=0.0)
        ck = str(tmp_path / "ck")
        cfg = Config(
            data_dir=d, num_feature_dim=16, num_workers=2, num_servers=2,
            num_iteration=3, learning_rate=0.5, l2_c=0.0, batch_size=-1,
            test_interval=0, sync_mode=True, checkpoint_dir=ck,
            checkpoint_interval=0, ps_timeout_ms=4000,
        )

        from distlr_tpu.train import ps_trainer

        real_grad = ps_trainer._np_dense_grad
        state = {"calls": 0, "crashed": False}

        def flaky_grad(*args, **kw):
            state["calls"] += 1
            if not state["crashed"] and state["calls"] == 3:
                state["crashed"] = True
                raise RuntimeError("injected crash before first checkpoint")
            return real_grad(*args, **kw)

        monkeypatch.setattr(ps_trainer, "_np_dense_grad", flaky_grad)
        group = ServerGroup(2, 2, ps_param_dim(cfg), learning_rate=0.5, sync=True)
        with group:
            with pytest.raises(Exception):
                run_ps_workers(cfg, group.hosts, range(2), save=False)
            assert state["crashed"]
            sidecar = os.path.join(ck, "ps_latest.json")
            assert not os.path.exists(sidecar)  # crash predates any ckpt

            monkeypatch.setattr(ps_trainer, "_np_dense_grad", real_grad)
            resumed = run_ps_workers(
                cfg, group.hosts, range(2), save=False, resume=True,
            )
        with open(sidecar) as f:
            sc = json.load(f)
        assert sc["attempt"] == 1
        assert sc["epoch"] == 3  # final checkpoint of the resumed run

        # Oracle: from-scratch run, fresh group, fresh checkpoint dir
        # (sync full-batch is deterministic; same Q2 deterministic init).
        ref = run_ps_local(
            cfg.replace(checkpoint_dir=str(tmp_path / "ck_ref")), save=False,
        )
        np.testing.assert_allclose(resumed[0], ref[0], rtol=1e-5, atol=1e-6)


class TestServerSupervisor:
    """Server-side crash recovery (VERDICT r2 #3): ServerSupervisor
    respawns SIGKILLed server ranks on their original ports and re-seeds
    the slice from a rolling snapshot — the complement of the
    worker-crash tests above.  The reference's outcome for a dead server
    is (like everything else) an eternal hang."""

    def test_sync_group_refused(self):
        from distlr_tpu.ps import ServerSupervisor

        with ServerGroup(1, 1, dim=4, sync=True) as g:
            with pytest.raises(ValueError, match="async"):
                ServerSupervisor(g)

    def _wait_event(self, sup, rank, event, deadline_s=10.0):
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            if any(r == rank and ev == event for _, r, ev in sup.events):
                return True
            time.sleep(0.05)
        return False

    def test_sigkill_respawn_reseeds_slice_from_snapshot(self):
        from distlr_tpu.ps import ServerSupervisor

        with ServerGroup(2, 1, dim=8, sync=False, learning_rate=1.0) as g:
            ports_before = list(g.ports)
            sup = ServerSupervisor(g, poll_interval=0.05, snapshot_interval=0.05)
            with KVWorker(g.hosts, 8, timeout_ms=5000, sync_group=False) as kv:
                kv.wait(kv.push_init(np.arange(8, dtype=np.float32)))
            with sup:
                time.sleep(0.4)  # a post-init snapshot lands
                g.procs[1].kill()  # SIGKILL rank 1 (keys 4..8)
                assert self._wait_event(sup, 1, "respawned")
                assert self._wait_event(sup, 1, "reseeded")
            assert g.ports == ports_before  # hosts string still valid
            assert all(g.alive())
            with KVWorker(g.hosts, 8, timeout_ms=5000, sync_group=False) as kv2:
                np.testing.assert_allclose(kv2.pull(), np.arange(8))
                kv2.shutdown_servers()

    def test_snapshot_skips_untouched_ranges(self):
        """Keyed snapshots (VERDICT r3 #6): a rank whose total_pushes
        counter hasn't moved since its last capture must NOT be re-pulled
        every interval — snapshot cost scales with write traffic, not
        key-space size.  Observed via the servers' total_pulls counters:
        after the first capture, idle cycles add zero pulls; pushing to
        one rank's range makes only THAT rank's pulls advance."""
        from distlr_tpu.ps import ServerSupervisor

        with ServerGroup(2, 1, dim=8, sync=False, learning_rate=1.0) as g:
            sup = ServerSupervisor(g, poll_interval=0.05,
                                   snapshot_interval=0.05)
            with KVWorker(g.hosts, 8, timeout_ms=5000, sync_group=False) as kv:
                kv.wait(kv.push_init(np.zeros(8, np.float32)))
                with sup:
                    # first capture lands, then several idle cycles
                    t0 = time.monotonic()
                    while not all(sup._snap_valid):
                        assert time.monotonic() - t0 < 10.0, "no snapshot"
                        time.sleep(0.02)
                    time.sleep(0.5)  # ~10 idle snapshot intervals
                    pulls_idle = [kv.stats(r)["total_pulls"] for r in (0, 1)]
                    time.sleep(0.5)
                    pulls_idle2 = [kv.stats(r)["total_pulls"] for r in (0, 1)]
                    assert pulls_idle2 == pulls_idle, (
                        "idle ranges were re-pulled every interval")
                    # touch ONLY rank 0's range (keys 0..4)
                    kv.wait(kv.push(np.ones(4, np.float32),
                                    keys=np.arange(4, dtype=np.uint64)))
                    time.sleep(0.5)
                    pulls_after = [kv.stats(r)["total_pulls"] for r in (0, 1)]
                    assert pulls_after[0] > pulls_idle2[0], (
                        "touched range was never re-captured")
                    assert pulls_after[1] == pulls_idle2[1], (
                        "untouched range was re-pulled")
                    kv.shutdown_servers()

    def test_snapshot_captures_healthy_ranks_while_one_is_down(self):
        """Per-rank capture isolation (r4 review finding): one dead rank
        must not fail the whole snapshot cycle — that would silently
        freeze the HEALTHY ranks' slices and unbound the
        snapshot_interval loss guarantee (e.g. after a rank exhausts
        max_respawns and is left down for hours)."""
        from distlr_tpu.ps import ServerSupervisor

        with ServerGroup(2, 1, dim=8, sync=False, learning_rate=1.0) as g:
            sup = ServerSupervisor(g)  # not started: drive captures directly
            with KVWorker(g.hosts, 8, timeout_ms=5000, sync_group=False) as kv:
                kv.wait(kv.push_init(np.arange(8, dtype=np.float32)))
            g.procs[1].kill()
            g.procs[1].wait(timeout=5)
            sup._try_snapshot()
            assert sup._snap_valid[0] and not sup._snap_valid[1]
            np.testing.assert_allclose(sup._snapshot[:4], np.arange(4))
            # rank 0 keeps absorbing updates; its slice must keep moving
            with KVWorker(f"127.0.0.1:{g.ports[0]}", 4, timeout_ms=5000,
                          sync_group=False) as kv0:
                kv0.wait(kv0.push(np.ones(4, np.float32)))  # w -= lr*1
            sup._try_snapshot()
            np.testing.assert_allclose(sup._snapshot[:4],
                                       np.arange(4) - 1.0)

    def test_sigkill_recovery_loses_at_most_snapshot_window(self):
        """The loss bound (VERDICT r3 #6): a SIGKILL-recovered rank loses
        at most the updates applied after its last snapshot capture.
        Deterministic accounting: lr=1 and unit gradients on key 0 make
        weight[0] = -(number of applied updates), so the recovered value
        must land in [-(n1+n2+n3), -(n1+n3)] — phase-A updates (snapshot
        confirmed to postdate them) and phase-C updates (post-recovery)
        can never be lost; only the n2 pushed inside the final snapshot
        window may be."""
        from distlr_tpu.ps import ServerSupervisor

        n1, n2, n3 = 5, 3, 4
        g_unit = np.array([1, 0, 0, 0], np.float32)  # key 0 -> rank 0
        with ServerGroup(2, 1, dim=4, sync=False, learning_rate=1.0) as g:
            sup = ServerSupervisor(g, poll_interval=0.05,
                                   snapshot_interval=0.05)
            with sup:
                with KVWorker(g.hosts, 4, timeout_ms=5000,
                              sync_group=False) as kv:
                    kv.wait(kv.push_init(np.zeros(4, np.float32)))
                    for _ in range(n1):  # phase A: blocking => applied
                        kv.wait(kv.push(g_unit))
                    t_a = time.monotonic()
                    # wait until rank 0's snapshot capture postdates
                    # phase A — those n1 updates are now unlosable
                    while sup._snap_at[0] <= t_a:
                        assert time.monotonic() - t_a < 10.0, "no snapshot"
                        time.sleep(0.02)
                    for _ in range(n2):  # phase B: inside the loss window
                        kv.wait(kv.push(g_unit))
                    g.procs[0].kill()
                assert self._wait_event(sup, 0, "respawned")
                assert self._wait_event(sup, 0, "reseeded")  # not zeros
                with KVWorker(g.hosts, 4, timeout_ms=5000,
                              sync_group=False) as kv2:
                    for _ in range(n3):  # phase C: post-recovery
                        kv2.wait(kv2.push(g_unit))
                    w0 = float(kv2.pull()[0])
                    kv2.shutdown_servers()
        applied = -w0
        assert n1 + n3 <= applied <= n1 + n2 + n3, (
            f"applied={applied}, bound=[{n1 + n3}, {n1 + n2 + n3}] "
            f"(events: {sup.events})")

    def _async_run_with_killer(self, tmp_path, kill_policy, *,
                               num_iteration, max_restarts,
                               max_respawns=3):
        """Shared scaffold for the SIGKILL recovery tests: synthetic
        data, a 2-worker/2-server async run with the supervisor
        attached, and a killer thread driving ``kill_policy(group,
        stop)`` until it returns or training ends.  Returns
        ``(results, evals, sup)``."""
        import threading

        from distlr_tpu.config import Config
        from distlr_tpu.data.synthetic import write_synthetic_shards
        from distlr_tpu.ps import ServerSupervisor
        from distlr_tpu.train.ps_trainer import ps_param_dim, run_ps_workers

        d = str(tmp_path / "data")
        write_synthetic_shards(d, 2400, 16, num_parts=2, seed=9, sparsity=0.0)
        evals = []
        cfg = Config(
            data_dir=d, num_feature_dim=16, num_workers=2, num_servers=2,
            num_iteration=num_iteration, learning_rate=0.2, l2_c=0.0,
            batch_size=100, test_interval=num_iteration, sync_mode=False,
            ps_timeout_ms=20_000,
        )
        group = ServerGroup(2, 2, ps_param_dim(cfg), learning_rate=0.2,
                            sync=False)
        stop = threading.Event()
        killer = threading.Thread(target=kill_policy, args=(group, stop))
        with group, ServerSupervisor(group, poll_interval=0.05,
                                     snapshot_interval=0.05,
                                     max_respawns=max_respawns) as sup:
            killer.start()
            try:
                results = run_ps_workers(
                    cfg, group.hosts, range(2), save=False,
                    max_restarts=max_restarts,
                    eval_fn=lambda ep, acc: evals.append((ep, acc)),
                )
            finally:
                stop.set()
                killer.join()
        assert all(r is not None for r in results.values())
        assert np.isfinite(results[0]).all()
        # trained, not reset-to-zero/corrupt: the dense synthetic config
        # reaches ~0.9+ by these epoch counts (cf. async convergence bands)
        assert evals and evals[-1][1] >= 0.75, evals
        return results, evals, sup

    def test_async_training_survives_server_sigkill(self, tmp_path):
        """End to end: SIGKILL a server mid-async-run with the supervisor
        attached; training completes with trained (not reset, not
        corrupt) weights."""
        killed = {"at_pushes": None}

        def kill_rank1_once(group, stop):
            # deterministic mid-run kill: wait for real training progress
            # (stats probe), then SIGKILL rank 1
            while not stop.is_set():
                try:
                    pushes = group.health(timeout_ms=1000)[1]["total_pushes"]
                except Exception:
                    pushes = 0
                if pushes >= 20:
                    killed["at_pushes"] = pushes
                    group.procs[1].kill()
                    return
                time.sleep(0.02)

        _, _, sup = self._async_run_with_killer(
            tmp_path, kill_rank1_once, num_iteration=40, max_restarts=5)
        assert killed["at_pushes"] is not None, "kill never fired (run too fast?)"
        assert any(ev == "respawned" for _, r, ev in sup.events), sup.events

    def test_repeated_kills_across_ranks_all_recover(self, tmp_path):
        """Chaos variant: three kills alternating across ranks during
        one async run.  Each death exercises a fresh respawn + keyed
        re-seed cycle; the run must still finish trained (respawn
        budget, rollback, and per-rank snapshots compose across
        repeated failures, not just one)."""
        kills = []

        def killer_loop(group, stop):
            # kill rank (k % 2) each time total pushes advance another
            # ~25 past the previous kill; exactly 3 kills
            next_at = 25
            while not stop.is_set() and len(kills) < 3:
                rank = len(kills) % 2
                try:
                    pushes = sum(
                        h["total_pushes"]
                        for h in group.health(timeout_ms=1000))
                except Exception:
                    pushes = 0
                if pushes >= next_at and group.procs[rank].poll() is None:
                    kills.append((rank, pushes))
                    group.procs[rank].kill()
                    next_at = pushes + 25
                time.sleep(0.05)

        _, _, sup = self._async_run_with_killer(
            tmp_path, killer_loop, num_iteration=60, max_restarts=8,
            max_respawns=5)
        assert len(kills) == 3, f"chaos never fired fully: {kills}"
        respawns = [r for _, r, ev in sup.events if ev == "respawned"]
        # A kill landing in the final poll window before the run ends may
        # be torn down with the group instead of respawned — tolerate
        # exactly one such tail race, never more.
        assert len(respawns) >= len(kills) - 1, (kills, sup.events)


class TestSupervisorEdgeCases:
    def test_double_sigkill_reseeds_both_via_retry(self):
        """Both ranks die within one poll window: each respawned rank
        must end up re-seeded from the snapshot, never left alive-but-
        uninitialized (which would install the next gradient push AS the
        weights).  Re-seeds are per-rank connections, so neither rank's
        recovery may depend on the other being up; a re-seed that does
        fail (e.g. the respawned process not yet accepting) is retried
        via _needs_reseed, not dropped."""
        from distlr_tpu.ps import ServerSupervisor

        with ServerGroup(2, 1, dim=8, sync=False, learning_rate=1.0) as g:
            sup = ServerSupervisor(g, poll_interval=0.05, snapshot_interval=0.05)
            with KVWorker(g.hosts, 8, timeout_ms=5000, sync_group=False) as kv:
                kv.wait(kv.push_init(np.arange(8, dtype=np.float32)))
            with sup:
                time.sleep(0.4)
                g.procs[0].kill()
                g.procs[1].kill()
                t0 = time.monotonic()
                while time.monotonic() - t0 < 10.0:
                    seeded = {r for _, r, ev in sup.events if ev == "reseeded"}
                    if seeded == {0, 1}:
                        break
                    time.sleep(0.05)
                assert seeded == {0, 1}, sup.events
            with KVWorker(g.hosts, 8, timeout_ms=5000, sync_group=False) as kv2:
                np.testing.assert_allclose(kv2.pull(), np.arange(8))
                kv2.shutdown_servers()

    def test_voluntary_shutdown_is_not_a_crash(self):
        """rank 0's shutdown_servers at the end of a clean run exits every
        server with code 0; the supervisor must not misread that as a
        group-wide crash and respawn uninitialized servers."""
        from distlr_tpu.ps import ServerSupervisor

        with ServerGroup(2, 1, dim=4, sync=False) as g:
            with ServerSupervisor(g, poll_interval=0.05,
                                  snapshot_interval=0.05) as sup:
                with KVWorker(g.hosts, 4, timeout_ms=5000,
                              sync_group=False) as kv:
                    kv.wait(kv.push_init(np.zeros(4, np.float32)))
                    kv.shutdown_servers()
                for p in g.procs:
                    p.wait(timeout=5)
                time.sleep(0.3)  # several poll cycles after retirement
                assert sup.events == [], sup.events
                assert all(p.poll() == 0 for p in g.procs)


class TestWireCorruption:
    """Wire values size allocations on the server; garbage must drop the
    connection, never kill the group member (a bad_alloc from
    resize(2^50) would take down the whole rank and trigger a pointless
    supervisor respawn)."""

    HEADER = "<IBBHIIQ"  # kv_protocol.h MsgHeader, 24 bytes packed
    MAGIC = 0xD157C0DE

    def _frame(self, op, num_keys):
        import struct
        return struct.pack(self.HEADER, self.MAGIC, op, 0, 0, 99, 1, num_keys)

    def test_huge_num_keys_drops_connection_not_server(self):
        import socket
        import struct

        with ServerGroup(1, 1, dim=8, sync=False) as g:
            port = g.ports[0]
            with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
                s.sendall(self._frame(op=1, num_keys=1 << 50))  # kPush
                # server must close on us, not crash
                assert s.recv(1) == b""
            with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
                # key id past the elasticity cap: same outcome
                s.sendall(self._frame(op=2, num_keys=1))  # kPull
                s.sendall(struct.pack("<Q", 1 << 60))
                assert s.recv(1) == b""
            assert all(g.alive())
            # and the server still serves real clients afterwards
            with KVWorker(g.hosts, 8, timeout_ms=5000, sync_group=False) as kv:
                assert kv.stats(0)["dim"] == 8
                kv.shutdown_servers()

    def test_unsorted_push_frame_grows_to_max_key(self):
        """Regression (r4 review): capacity used to grow to keys.back(),
        which assumes sorted keys — an unsorted frame like [100, 3] on a
        dim-8 server would write weights_[100] out of bounds.  The wire
        does not promise ordering, so the server must size by the
        frame's MAX key and apply both updates."""
        import socket
        import struct

        with ServerGroup(1, 1, dim=8, sync=False, learning_rate=1.0) as g:
            with socket.create_connection(("127.0.0.1", g.ports[0]),
                                          timeout=5) as s:
                # async push, keys [100, 3] (unsorted), grads [2.0, 5.0]
                s.sendall(self._frame(op=1, num_keys=2))
                s.sendall(struct.pack("<QQ", 100, 3))
                s.sendall(struct.pack("<ff", 2.0, 5.0))
                # first-ever push takes the init branch: seeds weights
                resp = s.recv(24)
                assert len(resp) == 24
            assert all(g.alive())
            with KVWorker(g.hosts, 101, timeout_ms=5000,
                          sync_group=False) as kv:
                w = kv.pull()
                assert w[100] == 2.0 and w[3] == 5.0  # init semantics
                kv.shutdown_servers()

    def test_alloc_failure_drops_connection_not_server(self):
        """A key just UNDER the elasticity cap passes every guard but
        demands a huge EnsureCapacity resize; the bad_alloc must drop
        the connection, not std::terminate the rank.  Deterministic via
        an address-space rlimit on a directly-spawned server."""
        import shlex
        import socket
        import struct
        import subprocess

        from distlr_tpu.ps.build import server_binary

        # ulimit via a shell wrapper, NOT preexec_fn: preexec_fn forces
        # a raw os.fork() in this (JAX-)multithreaded test process —
        # a documented deadlock risk — while a plain argv spawn uses
        # posix_spawn.
        cmd = (f"ulimit -v {1 << 20}; exec "  # 1 GiB of address space
               f"{shlex.quote(server_binary())} --port=0 --num_workers=1 "
               f"--dim=8 --sync=0")
        proc = subprocess.Popen(
            ["/bin/sh", "-c", cmd],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        try:
            line = proc.stdout.readline().strip()
            assert line.startswith("PORT "), line
            port = int(line.split()[1])
            with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
                # pull of key 2^31-1: under the default cap, but the
                # resize to ~16 GiB cannot fit in a 1 GiB address space
                s.sendall(self._frame(op=2, num_keys=1))
                s.sendall(struct.pack("<Q", (1 << 31) - 1))
                assert s.recv(1) == b""  # dropped, not served
            assert proc.poll() is None  # rank still alive
            # still serves real clients afterwards
            with KVWorker(f"127.0.0.1:{port}", 8, timeout_ms=5000,
                          sync_group=False) as kv:
                assert kv.stats(0)["dim"] == 8
                kv.shutdown_servers()
            proc.wait(timeout=10)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()


class TestConnectTimeout:
    def test_unresponsive_host_fails_fast(self, monkeypatch):
        """kv_connect to a host that drops SYNs must fail within the
        bounded connect timeout, not the kernel's minutes-long SYN-retry
        window (a DCN partition would otherwise freeze supervisor probes
        and worker restarts mid-op).  Reproduced locally by saturating a
        backlog-0 listener's accept queue: the kernel then silently
        drops further SYNs — exactly the partitioned-host picture."""
        import socket

        from distlr_tpu.ps.build import build_native

        build_native()  # keep a cold-start compile out of the timing window
        monkeypatch.setenv("DISTLR_CONNECT_TIMEOUT_MS", "400")
        lst = socket.socket()
        try:
            lst.bind(("127.0.0.1", 0))
            lst.listen(0)
            host, port = lst.getsockname()
            saturate = socket.create_connection((host, port))
            try:
                t0 = time.monotonic()
                with pytest.raises(ConnectionError):
                    KVWorker(f"{host}:{port}", 8, timeout_ms=1000,
                             sync_group=False)
                assert time.monotonic() - t0 < 5.0
            finally:
                saturate.close()
        finally:
            lst.close()
