"""Failure detection for the PS mode (SURVEY.md §5.3).

The reference has no failure handling at all: a dead worker in sync mode
deadlocks the BSP barrier forever (``src/main.cc:67-78`` waits for
exactly ``NumWorkers()`` pushes).  These tests pin the framework's
answer: client-side op timeouts that raise a *named* straggler error,
and a stats probe that stays answerable while the barrier is wedged.
"""

import time

import numpy as np
import pytest

from distlr_tpu.ps import KVWorker, PSTimeoutError, ServerGroup


def _wait_pending_zero(group, *, deadline_s: float = 5.0) -> int:
    """Poll server 0 until its deferred-push count drops to 0 (the
    disconnect rollback runs on the server's reader thread, which races
    a freshly-connected stats probe)."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        pending = group.health()[0]["pending_sync_pushes"]
        if pending == 0:
            return 0
        time.sleep(0.02)
    return pending


@pytest.fixture()
def sync_group_of_two():
    """Sync server expecting 2 workers — one never shows up."""
    with ServerGroup(1, 2, dim=8, sync=True, learning_rate=0.5) as group:
        yield group


class TestStragglerTimeout:
    def test_sync_push_times_out_with_named_straggler_error(self, sync_group_of_two):
        with KVWorker(sync_group_of_two.hosts, 8, client_id=0, timeout_ms=300) as kv:
            kv.push(np.zeros(8, np.float32))  # first push = init, replies at once
            t0 = time.monotonic()
            with pytest.raises(PSTimeoutError, match="straggler|BSP barrier"):
                kv.push(np.ones(8, np.float32))  # deferred: needs 2 workers
            assert time.monotonic() - t0 < 5.0  # timed out, not deadlocked

    def test_barrier_times_out_when_peer_missing(self, sync_group_of_two):
        with KVWorker(sync_group_of_two.hosts, 8, client_id=0, timeout_ms=300) as kv:
            with pytest.raises(PSTimeoutError):
                kv.barrier()

    def test_zero_timeout_means_blocking(self, sync_group_of_two):
        # timeout_ms=0 must not set a timeout: a pull (never deferred)
        # still completes after an arbitrary client-side pause.
        with KVWorker(sync_group_of_two.hosts, 8, client_id=0, timeout_ms=0) as kv:
            kv.push(np.zeros(8, np.float32))
            time.sleep(0.4)
            assert kv.pull().shape == (8,)


class TestStatsProbe:
    def test_stats_reflect_progress_and_survive_wedged_barrier(self):
        with ServerGroup(2, 2, dim=10, sync=True) as group:
            with KVWorker(group.hosts, 10, client_id=0, timeout_ms=500) as kv:
                kv.push(np.zeros(10, np.float32))  # init both servers
                kv.pull()
                with pytest.raises(PSTimeoutError):
                    kv.push(np.ones(10, np.float32))  # wedges the barrier
                # probe on a FRESH connection while the wedged push is
                # still pending (the timed-out client is alive, just
                # poisoned client-side)
                health = group.health(timeout_ms=1000)
                assert len(health) == 2
                for h, dim in zip(health, (5, 5)):
                    assert h["dim"] == dim
                    assert h["initialized"] == 1
                    assert h["pending_sync_pushes"] == 1  # the wedged push
                    assert h["total_pushes"] == 2
                    assert h["total_pulls"] == 1
            # once the wedged client disconnects, its deferred push is
            # rolled back (see TestWorkerRestartRecovery)
            assert _wait_pending_zero(group) == 0

    def test_alive_tracks_processes(self):
        group = ServerGroup(1, 1, dim=4, sync=False).start()
        assert group.alive() == [True]
        group.stop()
        assert group.alive() == []


class TestWorkerRestartRecovery:
    def test_reconnected_worker_is_not_double_counted(self, sync_group_of_two):
        """A worker that times out, reconnects, and re-pushes must count
        once: the server rolls the dead connection's deferred push out of
        the merge buffer (no rollback -> the barrier would release early
        with a duplicated gradient)."""
        hosts = sync_group_of_two.hosts
        with KVWorker(hosts, 8, client_id=0, timeout_ms=300) as kv:
            kv.push(np.zeros(8, np.float32))  # init
            with pytest.raises(PSTimeoutError):
                kv.push(np.ones(8, np.float32))  # deferred, then timeout
        # old connection closed -> server must have rolled its push back
        assert _wait_pending_zero(sync_group_of_two) == 0

        # restart: reconnect and train with BOTH workers present
        kv0 = KVWorker(hosts, 8, client_id=0, timeout_ms=3000)
        kv1 = KVWorker(hosts, 8, client_id=1, timeout_ms=3000)
        import threading

        g0 = np.full(8, 1.0, np.float32)
        g1 = np.full(8, 3.0, np.float32)
        t = threading.Thread(target=lambda: kv1.push(g1))
        t.start()
        kv0.push(g0)  # releases once both arrive
        t.join()
        w = kv0.pull()
        kv0.close()
        kv1.close()
        # exactly one mean update: -lr * (1+3)/2 = -0.5 * 2 = -1
        np.testing.assert_allclose(w, -1.0 * np.ones(8), rtol=1e-6)

    def test_poisoned_connection_fails_fast_after_timeout(self, sync_group_of_two):
        with KVWorker(sync_group_of_two.hosts, 8, client_id=0, timeout_ms=300) as kv:
            kv.push(np.zeros(8, np.float32))
            with pytest.raises(PSTimeoutError):
                kv.push(np.ones(8, np.float32))
            with pytest.raises(IOError, match="poisoned"):
                kv.pull()


class TestAsyncUnaffected:
    def test_async_single_worker_never_needs_peers(self):
        with ServerGroup(1, 4, dim=6, sync=False) as group:
            with KVWorker(group.hosts, 6, client_id=0, timeout_ms=1000) as kv:
                kv.push(np.zeros(6, np.float32))  # init
                kv.push(np.full(6, 2.0, np.float32))  # applied immediately
                w = kv.pull()
                np.testing.assert_allclose(w, -0.2 * 2.0 * np.ones(6), rtol=1e-6)
