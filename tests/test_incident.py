"""Incident engine + fleet-wide structured logging (ISSUE 18).

Covers the FleetLogger core (bounded ring, level gating, rate-limited
dedupe with suppressed counts, bounded dedupe table, journal record
cap, eager WARN+ flushes, stdlib tee with template dedupe identity,
dtrace trace/span stamping), the fleet-wide journal reader behind
``launch logs``, the incident engine (kHello clock-shift alignment,
exactly-one-bundle-per-seq idempotence, artifact collection across
every journal family, retention, manual drills), obs-agg's edge ->
settle -> assemble wiring (no re-trigger while an alert stays firing),
the ``launch logs`` / ``launch incident`` CLI contracts, and the
acceptance e2e: a real ps+serve+route+online fleet under a chaos plan
producing ONE bundle whose timeline orders chaos-fault -> alert-edge
-> autopilot rollback correctly.
"""

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from distlr_tpu.obs import dtrace, incident, profile
from distlr_tpu.obs import log as fleetlog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset():
    yield
    fleetlog.reset_for_tests()
    profile.reset_for_tests()
    dtrace.reset_for_tests()


def _counter_total(name: str) -> float:
    from distlr_tpu.obs.registry import get_registry

    fam = get_registry().get(name)
    if fam is None:
        return 0.0
    return sum(child.value for _v, child in fam.children())


def _journal_lines(run: str, stem: str) -> list[dict]:
    with open(os.path.join(run, "logs", stem + ".jsonl")) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# ---------------------------------------------------------------------------
# FleetLogger units
# ---------------------------------------------------------------------------

class TestFleetLogger:
    def test_validation(self):
        with pytest.raises(ValueError, match="level"):
            fleetlog.FleetLogger(None, "t", 0, level="loud")
        with pytest.raises(ValueError, match="ring"):
            fleetlog.FleetLogger(None, "t", 0, ring=0)
        with pytest.raises(ValueError, match="dedupe_s"):
            fleetlog.FleetLogger(None, "t", 0, dedupe_s=-1.0)

    def test_ring_bounded_and_keeps_below_level(self, tmp_path):
        lg = fleetlog.FleetLogger(str(tmp_path), "t", 0, ring=8,
                                  dedupe_s=0.0)
        for i in range(30):
            lg.debug_seen = lg.emit("debug", f"d{i}")  # below level=info
        lg.emit("info", "kept")
        lg.flush()
        ring = lg.tail(100)
        assert len(ring) == 8  # bounded
        assert ring[-1]["msg"] == "kept"
        # below-level records live in the ring but never in the journal
        recs = [d for d in _journal_lines(str(tmp_path), "t-0")
                if d["type"] == "record"]
        assert [r["msg"] for r in recs] == ["kept"]

    def test_dedupe_window_suppresses_then_closes_with_count(self):
        lg = fleetlog.FleetLogger(None, "t", 0, dedupe_s=0.3)
        first = lg.emit("info", "boom")
        assert "suppressed" not in first
        for _ in range(3):
            lg.emit("info", "boom")
        assert lg.stats()["suppressed"] == 3
        time.sleep(0.35)
        closing = lg.emit("info", "boom")
        assert closing["suppressed"] == 3

    def test_distinct_templates_do_not_collide(self):
        lg = fleetlog.FleetLogger(None, "t", 0, dedupe_s=5.0)
        a = lg.emit("info", "rank 1 timed out", template="rank %d timed out")
        b = lg.emit("info", "rank 2 timed out", template="rank %d timed out")
        c = lg.emit("info", "other message")
        assert "suppressed" not in a and "suppressed" not in c
        assert lg.stats()["suppressed"] == 1  # b collapsed into a's window
        assert b["msg"] == "rank 2 timed out"

    def test_dedupe_table_bounded(self, monkeypatch):
        monkeypatch.setattr(fleetlog, "DEDUPE_TABLE_MAX", 8)
        lg = fleetlog.FleetLogger(None, "t", 0, dedupe_s=0.05)
        for i in range(8):
            lg.emit("info", f"m{i}")
        time.sleep(0.1)  # all 8 windows expire with nothing pending
        for i in range(8, 13):
            lg.emit("info", f"m{i}")
        # the prune on insert drops expired no-pending entries
        assert len(lg._dedupe) <= 8

    def test_journal_record_cap_drops_loudly(self, tmp_path, monkeypatch):
        monkeypatch.setattr(fleetlog, "MAX_JOURNAL_RECORDS", 10)
        before = _counter_total("distlr_log_journal_dropped_total")
        lg = fleetlog.FleetLogger(str(tmp_path), "t", 0, dedupe_s=0.0)
        for i in range(15):
            lg.emit("info", f"m{i}")
        lg.flush()
        recs = [d for d in _journal_lines(str(tmp_path), "t-0")
                if d["type"] == "record"]
        assert len(recs) == 10
        assert _counter_total("distlr_log_journal_dropped_total") \
            - before == 5
        # the ring keeps running past the cap
        assert lg.tail(1)[0]["msg"] == "m14"

    def test_warn_flushes_eagerly_info_buffers(self, tmp_path):
        lg = fleetlog.FleetLogger(str(tmp_path), "t", 0, dedupe_s=0.0)
        # the meta line is flushed eagerly at open
        assert _journal_lines(str(tmp_path), "t-0")[0]["type"] == "meta"
        lg.emit("info", "buffered")
        assert len(_journal_lines(str(tmp_path), "t-0")) == 1
        lg.emit("warning", "urgent")
        lines = _journal_lines(str(tmp_path), "t-0")
        assert [d.get("msg") for d in lines[1:]] == ["buffered", "urgent"]
        lg.close()

    def test_stdlib_tee_keeps_stderr_handlers(self, tmp_path):
        from distlr_tpu.utils.logging import get_logger

        log = get_logger("distlr_tpu.test_incident_tee")
        handlers_before = list(log.handlers)
        fleetlog.configure(str(tmp_path), "worker", 3, dedupe_s=5.0)
        try:
            for i in range(3):
                log.warning("rank %d timed out", i)
            fleetlog.flush()
            recs = [d for d in _journal_lines(str(tmp_path), "worker-3")
                    if d["type"] == "record"]
            # pre-format template is the dedupe identity: one journaled
            assert len(recs) == 1
            assert recs[0]["msg"] == "rank 0 timed out"
            assert recs[0]["logger"] == "distlr_tpu.test_incident_tee"
            assert recs[0]["role"] == "worker" and recs[0]["rank"] == 3
            assert fleetlog.fleet_logger().stats()["suppressed"] == 2
        finally:
            fleetlog.stop()
        # the human-readable stderr path is untouched, tee detached
        assert [h for h in log.handlers
                if not isinstance(h, fleetlog._JournalHandler)] \
            == handlers_before
        assert not any(isinstance(h, fleetlog._JournalHandler)
                       for h in log.handlers)

    def test_trace_ids_stamped(self, tmp_path):
        run = str(tmp_path)
        dtrace.configure(run, "serve", 0, sample=1.0)
        lg = fleetlog.FleetLogger(run, "serve", 0, dedupe_s=0.0)
        bare = lg.emit("info", "outside any trace")
        assert "trace" not in bare
        ctx = dtrace.new_trace()
        with dtrace.use(ctx), dtrace.span("req.handle"):
            rec = lg.emit("info", "inside the request")
        assert rec["trace"] == f"{ctx.trace_id:016x}"
        assert len(rec["span"]) == 16
        lg.close()

    def test_module_emit_noop_until_configured(self, tmp_path):
        assert not fleetlog.is_configured()
        assert fleetlog.emit("info", "dropped") is None
        lg = fleetlog.configure(str(tmp_path), "cli", 0)
        try:
            assert fleetlog.is_configured()
            assert fleetlog.emit("info", "kept")["role"] == "cli"
            assert fleetlog.fleet_logger() is lg
        finally:
            fleetlog.stop()
        assert fleetlog.emit("info", "dropped again") is None

    def test_read_records_merges_filters_and_tails(self, tmp_path):
        run = str(tmp_path)
        a = fleetlog.FleetLogger(run, "serve", 0, level="debug",
                                 dedupe_s=0.0)
        b = fleetlog.FleetLogger(run, "online", 1, level="debug",
                                 dedupe_s=0.0)
        a.emit("info", "pull ok")
        b.emit("warning", "claim stolen")
        a.emit("error", "pull FAILED hard")
        a.flush(), b.flush()
        recs = fleetlog.read_records(run)
        assert [r["msg"] for r in recs] == [
            "pull ok", "claim stolen", "pull FAILED hard"]
        assert [r["msg"] for r in fleetlog.read_records(run,
                                                        level="warning")] \
            == ["claim stolen", "pull FAILED hard"]
        assert [r["msg"] for r in fleetlog.read_records(run, grep="FAILED")] \
            == ["pull FAILED hard"]
        assert [r["msg"] for r in fleetlog.read_records(run, limit=1)] \
            == ["pull FAILED hard"]
        a.close(), b.close()


# ---------------------------------------------------------------------------
# incident engine units
# ---------------------------------------------------------------------------

def _write_jsonl(path: str, docs: list[dict]) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for d in docs:
            f.write(json.dumps(d) + "\n")


class TestIncidentEngine:
    def test_clock_shift_merge(self, tmp_path):
        """A peer journal whose meta.listen port was clock-probed is
        shifted onto the observer's clock — record for record the PR-8
        kHello offsets — so a skewed rank's WARN sorts where it
        actually happened."""
        agg = str(tmp_path / "agg")
        ps = str(tmp_path / "ps")
        t0 = 1_700_000_000.0
        # the observer measured ps's clock +2s ahead
        _write_jsonl(os.path.join(agg, "spans", "agg-0.jsonl"), [
            {"type": "meta", "role": "agg", "rank": 0},
            {"type": "clock", "peer": "10.0.0.2:9001", "offset_s": 2.0},
        ])
        _write_jsonl(os.path.join(ps, "spans", "ps-0.jsonl"), [
            {"type": "meta", "role": "ps", "rank": 0,
             "listen": "0.0.0.0:9001"},
        ])
        shifts, offsets = incident.clock_shifts([agg, ps])
        assert offsets == {"9001": 2.0}
        assert shifts == {"agg-0": 0.0, "ps-0": -2.0}
        # ps logged at raw ts t0+1.5 on its own (fast) clock: truly
        # t0-0.5, i.e. BEFORE agg's t0 record
        _write_jsonl(os.path.join(agg, "logs", "agg-0.jsonl"), [
            {"type": "record", "ts": t0, "level": "warning",
             "role": "agg", "rank": 0, "logger": "x", "msg": "edge seen"},
        ])
        _write_jsonl(os.path.join(ps, "logs", "ps-0.jsonl"), [
            {"type": "record", "ts": t0 + 1.5, "level": "error",
             "role": "ps", "rank": 0, "logger": "x", "msg": "died first"},
        ])
        out = incident.assemble([agg, ps], seq=0, reason="skewtest",
                                detected_ts=t0 + 1.0,
                                per_dir_seqs=[None, None])
        assert out == incident.bundle_dir(agg, 0)
        doc = incident.load(agg, 0)
        logs = [e for e in doc["timeline"] if e["kind"] == "log"]
        assert [e["src"] for e in logs] == ["ps-0", "agg-0"]
        assert logs[0]["t"] == pytest.approx(t0 - 0.5)
        assert doc["clock_shifts"] == {"ps-0": -2.0}
        ts = [e["t"] for e in doc["timeline"]]
        assert ts == sorted(ts)

    def test_assemble_is_idempotent_per_seq(self, tmp_path):
        run = str(tmp_path)
        _write_jsonl(os.path.join(run, "logs", "a-0.jsonl"), [
            {"type": "record", "ts": 100.0, "level": "warning",
             "role": "a", "rank": 0, "logger": "x", "msg": "w"},
        ])
        first = incident.assemble(run, seq=4, reason="r",
                                  detected_ts=100.0)
        assert first is not None
        # the exactly-one-bundle contract: same seq assembles ONCE
        assert incident.assemble(run, seq=4, reason="r",
                                 detected_ts=101.0) is None
        assert [d["seq"] for d in incident.list_incidents(run)] == [4]
        assert incident.latest_seq(run) == 4

    def test_assemble_collects_every_artifact_family(self, tmp_path):
        from distlr_tpu.autopilot.actuators import Actuators
        from distlr_tpu.autopilot.daemon import AutopilotDaemon
        from distlr_tpu.autopilot.policy import PolicyConfig, PolicyEngine

        run = str(tmp_path)
        dtrace.configure(run, "worker", 0, sample=1.0)
        profile.configure(run, "worker", 0, hz=50, window_s=30,
                          burst_s=0.3)
        fleetlog.configure(run, "worker", 0, dedupe_s=0.0)
        ctx = dtrace.new_trace()
        with dtrace.use(ctx), dtrace.span("train.step"):
            fleetlog.emit("warning", "step latency blew the budget",
                          logger="worker.train")
        dtrace.instant("chaos.reset", tags={"link": 0, "fault": 2})
        # a real autopilot decision, journaled through the daemon so the
        # line carries BOTH the policy clock "t" and the wall "ts" the
        # collector anchors on
        daemon = AutopilotDaemon(
            PolicyEngine(PolicyConfig(hysteresis_ticks=1, cooldown_s=0.0)),
            _ScriptActuators({"ps": 1, "engine": 1, "worker": 1}),
            fetch=lambda: {"ranks": [{"role": "online", "rank": 0,
                                      "shard_lag": 50.0}]},
            journal_dir=run, clock=time.monotonic)
        decision = daemon.tick_once()
        assert decision.rule == "worker_up"
        _write_jsonl(os.path.join(run, "rollout", "ramp.jsonl"), [
            {"t": time.time(), "event": "stage", "stage": 1,
             "weight": 0.25},
        ])
        detected = time.time()
        dtrace.trigger(run, alert="distlr_alert_test")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not [
                f for f in os.listdir(os.path.join(run, "flightrec"))
                if f.startswith("worker-0-")]:
            time.sleep(0.05)
        time.sleep(0.6)  # the burst window closes
        profile.stop()
        dtrace.flush()
        fleetlog.flush()
        out = incident.assemble(
            run, seq=0, reason="distlr_alert_test", detected_ts=detected,
            alerts=[{"name": "distlr_alert_test", "firing": True}],
            settle_s=3.0)
        assert out is not None
        doc = incident.load(run, 0)
        kinds = doc["events"]
        for kind in ("alert_edge", "chaos", "log", "flight_dump",
                     "profiler_burst", "autopilot", "rollout"):
            assert kinds.get(kind, 0) >= 1, (kind, kinds)
        assert doc["flight_dumps"] and doc["bursts"]
        ts = [e["t"] for e in doc["timeline"]]
        assert ts == sorted(ts)
        # the daemon's wall anchor is what placed the decision in the
        # window — the policy-clock "t" (monotonic) lies far outside it
        ap = [e for e in doc["timeline"] if e["kind"] == "autopilot"]
        assert ap and ap[0]["rule"] == "worker_up"
        assert abs(ap[0]["t"] - detected) < 30.0
        text = open(os.path.join(out, "POSTMORTEM.md")).read()
        for heading in ("## Detection", "## Evidence", "## Actions taken",
                        "## Timeline"):
            assert heading in text
        assert "**distlr_alert_test**" in text
        assert "worker up -> 2" in text
        assert "step latency blew the budget" in text

    def test_render_rebuilds_postmortem_and_prune_retains(self, tmp_path):
        run = str(tmp_path)
        _write_jsonl(os.path.join(run, "logs", "a-0.jsonl"), [
            {"type": "record", "ts": 50.0, "level": "error", "role": "a",
             "rank": 0, "logger": "x", "msg": "w"},
        ])
        for seq in range(3):
            assert incident.assemble(run, seq=seq, reason=f"r{seq}",
                                     detected_ts=50.0 + seq) is not None
        pm = os.path.join(incident.bundle_dir(run, 2), "POSTMORTEM.md")
        os.remove(pm)
        assert incident.render(run, 2) == pm
        assert os.path.exists(pm)
        assert incident.render(run, 9) is None
        assert incident.prune(run, keep=1) == 2
        assert [d["seq"] for d in incident.list_incidents(run)] == [2]

    def test_manual_trigger_drill(self, tmp_path):
        run = str(tmp_path)
        dtrace.configure(run, "worker", 0, sample=0.0)
        with dtrace.span("warm.ring"):
            pass
        out = incident.manual_trigger(run, "drill", settle_s=0.8)
        assert out is not None
        doc = incident.load(run, 0)
        assert doc["trigger"] == "manual" and doc["reason"] == "drill"
        assert doc["events"].get("flight_dump", 0) >= 1
        # the drill's seq is taken: a second drill bumps to seq 1
        out2 = incident.manual_trigger(run, "drill2", settle_s=0.6)
        assert out2 is not None and incident.latest_seq(run) == 1


class _ScriptActuators:
    """test_autopilot's scripted Actuators stance: apply() mutates the
    counts current() reports, so the policy sees its actions land."""

    def __init__(self, counts):
        self.counts = dict(counts)
        self.applied = []

    def current(self):
        return dict(self.counts)

    def apply(self, actuator, to_count):
        self.applied.append((actuator, int(to_count)))
        self.counts[actuator] = int(to_count)
        return f"scripted {actuator}={to_count}"

    def close(self):
        pass


# ---------------------------------------------------------------------------
# obs-agg wiring: edge -> settle -> assemble, no re-trigger while firing
# ---------------------------------------------------------------------------

class TestScraperIncidents:
    def test_edge_assembles_once_while_alert_stays_firing(self, tmp_path):
        from distlr_tpu.obs import write_metrics_snapshot
        from distlr_tpu.obs.federate import AlertThresholds, FleetScraper
        from distlr_tpu.obs.registry import get_registry

        run = str(tmp_path)
        dtrace.configure(run, "worker", 0, sample=0.0)
        with dtrace.span("warm.ring"):
            pass
        fleetlog.configure(run, "worker", 0)
        try:
            # the structurally-0 supervisor gave-up alert: fires on any
            # count — the cheapest deterministic edge (test_profile's)
            get_registry().counter(
                "distlr_ps_supervisor_events_total", "", ("event",)
            ).labels(event="gave-up").inc()
            os.makedirs(os.path.join(run, "snapshots"), exist_ok=True)
            write_metrics_snapshot(
                os.path.join(run, "snapshots", "worker-0.json"),
                get_registry())
            scraper = FleetScraper(run, thresholds=AlertThresholds(),
                                   incident_settle_s=0.4)
            scraper.scrape_once()  # the edge: queued, not yet assembled
            assert incident.latest_seq(run) is None
            deadline = time.monotonic() + 8
            while incident.latest_seq(run) is None \
                    and time.monotonic() < deadline:
                time.sleep(0.1)
                scraper.scrape_once()
            assert incident.latest_seq(run) == 0
            doc = incident.load(run, 0)
            assert doc["events"].get("flight_dump", 0) >= 1
            # WARN+ records of this process (obs-agg's own edge warning
            # among them) rode into the bundle
            assert doc["events"].get("log", 0) >= 1
            # a STILL-firing alert on later scrapes is not a new edge:
            # exactly one bundle, ever
            for _ in range(3):
                time.sleep(0.2)
                scraper.scrape_once()
            assert os.listdir(os.path.join(run, "incidents")) == ["0000"]
            # fleet.json carries the incident seq for `launch top`
            assert scraper.fleet_json()["incident"]["last"] == 0
        finally:
            fleetlog.stop()


# ---------------------------------------------------------------------------
# CLI contracts
# ---------------------------------------------------------------------------

def _cli_env():
    return {**os.environ, "JAX_PLATFORMS": "cpu", "DISTLR_CPU_DEVICES": "1"}


class TestLogsCLI:
    def test_launch_logs_trace_e2e(self, tmp_path):
        """One request's log+span story: records stamped inside the
        trace interleave with that trace's spans, across a subprocess
        CLI invocation."""
        run = str(tmp_path)
        dtrace.configure(run, "serve", 0, sample=1.0)
        fleetlog.configure(run, "serve", 0, dedupe_s=0.0)
        try:
            ctx = dtrace.new_trace()
            with dtrace.use(ctx), dtrace.span("req.score"):
                rec = fleetlog.emit("info", "scored request 7",
                                    logger="serve.engine")
            fleetlog.emit("info", "unrelated background chatter")
            dtrace.flush()
            fleetlog.flush()
        finally:
            fleetlog.stop()
        trace_id = rec["trace"]
        out = subprocess.run(
            [sys.executable, "-m", "distlr_tpu.launch", "logs",
             "--obs-run-dir", run, "--trace", trace_id, "--json"],
            capture_output=True, text=True, cwd=REPO, env=_cli_env(),
            timeout=120)
        assert out.returncode == 0, out.stderr
        events = [json.loads(ln) for ln in out.stdout.splitlines()
                  if ln.strip()]
        kinds = {e.get("kind", "record") for e in events}
        assert "span" in kinds  # the trace's spans interleaved
        msgs = [e.get("msg") for e in events if "msg" in e]
        assert msgs == ["scored request 7"]
        spans = [e for e in events if e.get("kind") == "span"]
        assert spans[0]["name"] == "req.score"
        assert spans[0]["trace"] == trace_id
        # an unknown trace matches nothing: exit 1
        miss = subprocess.run(
            [sys.executable, "-m", "distlr_tpu.launch", "logs",
             "--obs-run-dir", run, "--trace", "00000000deadbeef"],
            capture_output=True, text=True, cwd=REPO, env=_cli_env(),
            timeout=120)
        assert miss.returncode == 1

    def test_launch_logs_filters_inprocess(self, tmp_path, capsys):
        from distlr_tpu import launch

        run = str(tmp_path)
        lg = fleetlog.FleetLogger(run, "serve", 0, dedupe_s=0.0)
        lg.emit("info", "pull ok")
        lg.emit("warning", "pull DEGRADED")
        lg.emit("error", "pull failed")
        lg.close()
        assert launch.main(["logs", "--obs-run-dir", run,
                            "--level", "warning", "--json"]) == 0
        lines = [json.loads(ln) for ln in
                 capsys.readouterr().out.splitlines() if ln.strip()]
        assert [r["msg"] for r in lines] == ["pull DEGRADED", "pull failed"]
        assert launch.main(["logs", "--obs-run-dir", run,
                            "--grep", "DEGRADED"]) == 0
        assert "pull DEGRADED" in capsys.readouterr().out
        assert launch.main(["logs", "--obs-run-dir", run, "--tail", "1",
                            "--json"]) == 0
        lines = [json.loads(ln) for ln in
                 capsys.readouterr().out.splitlines() if ln.strip()]
        assert [r["msg"] for r in lines] == ["pull failed"]
        # nothing matched -> 1; no run dir -> 2
        assert launch.main(["logs", "--obs-run-dir", run,
                            "--grep", "nope"]) == 1
        assert launch.main(["logs"]) == 2


class TestIncidentCLI:
    def test_list_show_render_contract(self, tmp_path, capsys):
        from distlr_tpu import launch

        run = str(tmp_path)
        assert launch.main(["incident", "list",
                            "--obs-run-dir", run]) == 1  # nothing yet
        _write_jsonl(os.path.join(run, "logs", "a-0.jsonl"), [
            {"type": "record", "ts": 60.0, "level": "warning", "role": "a",
             "rank": 0, "logger": "x", "msg": "w"},
        ])
        assert incident.assemble(run, seq=0, reason="drill",
                                 detected_ts=60.0) is not None
        capsys.readouterr()
        assert launch.main(["incident", "list", "--obs-run-dir", run]) == 0
        listing = capsys.readouterr().out
        assert "0000" in listing and "drill" in listing
        assert launch.main(["incident", "show", "--obs-run-dir", run]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["seq"] == 0 and doc["timeline"]
        pm = os.path.join(incident.bundle_dir(run, 0), "POSTMORTEM.md")
        os.remove(pm)
        assert launch.main(["incident", "render",
                            "--obs-run-dir", run]) == 0
        assert os.path.exists(pm)
        assert "INCIDENT" in capsys.readouterr().out
        assert launch.main(["incident", "show", "--seq", "7",
                            "--obs-run-dir", run]) == 1
        assert launch.main(["incident", "list"]) == 2  # needs run dir

    def test_trigger_drill_cli(self, tmp_path, capsys):
        from distlr_tpu import launch

        run = str(tmp_path)
        dtrace.configure(run, "worker", 0, sample=0.0)
        with dtrace.span("warm.ring"):
            pass
        assert launch.main(["incident", "--trigger", "game-day",
                            "--incident-settle", "0.6",
                            "--obs-run-dir", run]) == 0
        assert "INCIDENT" in capsys.readouterr().out
        doc = incident.load(run, 0)
        assert doc["reason"] == "game-day" and doc["trigger"] == "manual"


# ---------------------------------------------------------------------------
# acceptance e2e: chaos fleet -> one bundle, correctly ordered
# ---------------------------------------------------------------------------

def _read_announcement(proc, prefix: str, deadline_s: float = 120.0) -> str:
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(f"process exited before announcing "
                               f"{prefix!r} (rc={proc.poll()})")
        line = line.strip()
        if line.startswith(prefix):
            return line[len(prefix):].strip()
    raise RuntimeError(f"timed out waiting for {prefix!r}")


def _plant_shards(shard_dir: str, start: int, n: int) -> None:
    """Joined feedback shards, written atomically so the online
    trainer never reads a torn file."""
    os.makedirs(shard_dir, exist_ok=True)
    for i in range(start, start + n):
        body = "".join(
            f"{(i + j) % 2} " + " ".join(
                f"{k}:{0.1 * ((i + j + k) % 7):.1f}" for k in range(1, 9))
            + "\n" for j in range(3))
        tmp = os.path.join(shard_dir, f".shard-{i:05d}.tmp")
        with open(tmp, "w") as f:
            f.write(body)
        os.replace(tmp, os.path.join(shard_dir, f"shard-{i:05d}.libsvm"))


class TestIncidentAcceptance:
    def test_chaos_fleet_one_bundle_ordered_postmortem(self, tmp_path):
        """The ISSUE-18 acceptance run: a real 4-role fleet (each role
        its own process) whose PS links run through chaos fabrics; the
        injected resets drive the ps-retry-rate alert, obs-agg's edge
        assembles exactly ONE bundle, and its POSTMORTEM timeline
        orders chaos-fault -> alert-edge -> autopilot rollback."""
        from distlr_tpu.autopilot.daemon import AutopilotDaemon
        from distlr_tpu.autopilot.policy import PolicyConfig, PolicyEngine
        from distlr_tpu.chaos import ChaosFabric, parse_plan
        from distlr_tpu.obs.federate import AlertThresholds, FleetScraper
        from distlr_tpu.ps import KVWorker

        d = 64
        run = str(tmp_path / "run")
        os.makedirs(run)
        shards = str(tmp_path / "shards")
        os.makedirs(shards)

        # this process is the obs-agg rank: traces (the fabrics journal
        # their chaos instants here), structured logs (federate's edge
        # warning), and an armed profiler (the incident's burst ref)
        dtrace.configure(run, "agg", 0, sample=0.0)
        fleetlog.configure(run, "agg", 0)
        profile.configure(run, "agg", 0, hz=25, window_s=30, burst_s=0.3)

        # serve's PS link: every op from #8 on is severed -> its weight
        # watcher exhausts the retry budget (2 in-place retries per
        # poll, then the DEGRADED warning) and the fleet retry ratio
        # climbs monotonically.  online's link: sparse resets -> its
        # pushes absorb unknown-outcome faults without dying.
        serve_plan = parse_plan({"faults": [
            {"kind": "reset", "after_ops": n} for n in range(8, 320)]})
        # sparse: after any reset the next 12 ops are clean, so the
        # retry ladder always lands a re-issue — online jitters but
        # never dies (a dense plan can align resets with every re-issue)
        online_plan = parse_plan({"faults": [
            {"kind": "reset", "after_ops": n} for n in range(26, 400, 13)]})

        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "DISTLR_CPU_DEVICES": "1"}
        common = ["--obs-run-dir", run, "--num-feature-dim", str(d),
                  "--model", "binary_lr"]
        procs: list[subprocess.Popen] = []

        def launch_role(name: str, *args) -> subprocess.Popen:
            p = subprocess.Popen(
                [sys.executable, "-m", "distlr_tpu.launch", *args],
                stdout=subprocess.PIPE,
                stderr=open(str(tmp_path / f"{name}.stderr"), "w"),
                text=True, cwd=REPO, env=env)
            procs.append(p)
            return p

        # a fully severed serve link re-issues every pull twice, so the
        # cumulative retry ratio can exceed ANY finite probability-style
        # bound: quiet means 1e9, not 1.1
        quiet = AlertThresholds(
            barrier_wait_ratio=1e9, push_error_rate=1.1, scrape_stale_s=1e9,
            weight_age_ratio=1e9, retry_rate=1e9, shadow_psi=1e9)
        armed = AlertThresholds(
            barrier_wait_ratio=1e9, push_error_rate=1.1, scrape_stale_s=1e9,
            weight_age_ratio=1e9, retry_rate=0.05, shadow_psi=1e9)

        try:
            ps = launch_role("ps", "ps-server", "--async",
                             "--num-workers", "1", *common)
            hosts = _read_announcement(ps, "HOSTS ")
            # seed THROUGH the direct hosts: bring-up costs no fault ops
            with KVWorker(hosts, d, client_id=9, sync_group=False) as kv:
                kv.push_init(np.zeros(d, np.float32))
            with ChaosFabric(hosts, serve_plan) as fab_serve, \
                    ChaosFabric(hosts, online_plan) as fab_online:
                srv = launch_role(
                    "serve", "serve", "--ps-hosts", fab_serve.hosts,
                    "--reload-interval", "1.5",
                    "--ps-retry-attempts", "2",
                    "--ps-retry-backoff", "20", *common)
                online = launch_role(
                    "online", "online", "--hosts", fab_online.hosts,
                    "--shard-dir", shards, "--poll-interval", "1.0",
                    "--ps-retry-attempts", "5",
                    "--ps-retry-backoff", "20", *common)
                serve_addr = _read_announcement(srv, "SERVING ")
                rt = launch_role("route", "route",
                                 "--replicas", serve_addr, *common)
                route_addr = _read_announcement(rt, "ROUTING ")
                _read_announcement(online, "ONLINE ")

                # liveness traffic through the router
                host, port = route_addr.rsplit(":", 1)
                with socket.create_connection((host, int(port)),
                                              timeout=30.0) as s:
                    f = s.makefile("rwb")
                    for i in range(8):
                        f.write(f"ID warm-{i} 1:0.5 2:0.25 3:0.1\n"
                                .encode())
                        f.flush()
                        f.readline()

                scraper = FleetScraper(run, thresholds=quiet,
                                       incident_settle_s=2.5)
                daemon = AutopilotDaemon(
                    PolicyEngine(PolicyConfig(
                        hysteresis_ticks=1, cooldown_s=0.0,
                        rollback_window_s=600.0, lag_high=3.0)),
                    _ScriptActuators({"ps": 1, "engine": 1, "worker": 1}),
                    fetch=scraper.fleet_json,
                    alert_poll=lambda: [
                        a["name"]
                        for a in scraper.fleet_json().get("alerts", [])
                        if a.get("firing")],
                    journal_dir=run)

                # phase 2: a feedback backlog arms the worker band; the
                # autopilot scales BEFORE any alert fires (the action a
                # later rollback undoes).  The planted orphan claim is
                # online's guaranteed WARN: reclaimed as owner-presumed-
                # dead on its next cycle.
                orphan = os.path.join(shards, "shard-orphan.libsvm.claim")
                with open(orphan, "w") as f:
                    f.write("1 1:0.5 2:0.25\n")
                os.utime(orphan, (time.time() - 3600, time.time() - 3600))
                # a backlog the trainer cannot out-consume: a big batch
                # plus a steady trickle, so the shard_lag gauge holds a
                # nonzero scan value across scrape cycles
                _plant_shards(shards, 0, 60)
                planted = 60
                decision = None
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    scraper.scrape_once()
                    decision = daemon.tick_once()
                    if decision.rule == "worker_up":
                        break
                    _plant_shards(shards, planted, 2)
                    planted += 2
                    time.sleep(0.3)
                assert decision is not None \
                    and decision.rule == "worker_up", (
                        "no worker_up before chaos: "
                        f"last={decision and decision.to_json()}")

                # phase 3: burn ops into the reset bands, then arm the
                # retry-rate alert.  serve's polls now exhaust their
                # retries every cycle, so the fleet ratio only climbs.
                _plant_shards(shards, 5000, 20)
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline \
                        and not any(e[1] == "reset"
                                    for e in fab_serve.events()):
                    time.sleep(0.3)
                assert any(e[1] == "reset" for e in fab_serve.events()), \
                    "no serve-link reset fired"
                scraper.thresholds = armed

                detected = None
                deadline = time.monotonic() + 90
                while time.monotonic() < deadline:
                    scraper.scrape_once()
                    dtrace.flush()
                    fleet = scraper.fleet_json()
                    firing = [a["name"] for a in fleet.get("alerts", [])
                              if a.get("firing")]
                    if detected is None and \
                            "distlr_alert_ps_retry_rate" in firing:
                        detected = time.time()
                    # tick only once the alert is visible: a pre-edge
                    # tick would scale workers AGAIN (backlog is still
                    # high) and the rollback would undo 3->2, not 2->1
                    if firing:
                        daemon.tick_once()
                    if incident.latest_seq(run) is not None:
                        break
                    time.sleep(0.3)
                assert detected is not None, "retry-rate alert never fired"
                assert incident.latest_seq(run) == 0, "no bundle assembled"

                # a still-firing alert on later scrapes is not a new edge
                for _ in range(3):
                    scraper.scrape_once()
                    time.sleep(0.2)
                assert os.listdir(os.path.join(run, "incidents")) \
                    == ["0000"]
        finally:
            for p in procs:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
            for p in procs:
                try:
                    p.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
                if p.stdout:
                    p.stdout.close()
                if p.stderr:
                    p.stderr.close()
            profile.stop()
            fleetlog.stop()

        doc = incident.load(run, 0)
        events = doc["timeline"]
        ts = [e["t"] for e in events]
        assert ts == sorted(ts), "timeline is not clock-ordered"

        edges = [e for e in events if e["kind"] == "alert_edge"]
        assert len(edges) == 1
        edge_t = edges[0]["t"]
        assert "distlr_alert_ps_retry_rate" in edges[0]["alerts"]

        # chaos-fault -> alert-edge: the faults that CAUSED the alert
        # precede it on the timeline
        chaos = [e for e in events if e["kind"] == "chaos"]
        assert chaos and any(e["t"] < edge_t for e in chaos)
        assert any(e["fault"] == "chaos.reset" for e in chaos)

        # alert-edge -> rollback: the autopilot undid its youngest
        # action after the edge
        rollbacks = [e for e in events if e["kind"] == "autopilot"
                     and e.get("rule") == "rollback_on_alert"]
        assert rollbacks, "no rollback decision in the bundle"
        assert rollbacks[0]["t"] > edge_t
        assert rollbacks[0]["action"]["actuator"] == "worker"
        assert rollbacks[0]["action"]["to"] == 1

        # correlated WARN+ logs from >= 3 roles of the same fleet
        warn_roles = {e["src"].rsplit("-", 1)[0] for e in events
                      if e["kind"] == "log"
                      and e["level"] in ("warning", "error")}
        assert len(warn_roles) >= 3, warn_roles

        # the bundle cross-references the PR-8 flight dump and the PR-9
        # burst for the SAME incident seq
        dump_roles = {e["src"].rsplit("-", 1)[0] for e in events
                      if e["kind"] == "flight_dump"}
        assert len(dump_roles) >= 3, dump_roles
        assert doc["flight_dumps"]
        assert doc["bursts"], "no profiler burst ref for the seq"
        assert doc["per_dir_seqs"] == [0]

        text = open(os.path.join(doc["path"], "POSTMORTEM.md")).read()
        for heading in ("## Detection", "## Evidence", "## Actions taken",
                        "## Timeline"):
            assert heading in text
        assert "rollback_on_alert" in text
        assert "distlr_alert_ps_retry_rate" in text

        # `launch incident render` reproduces the postmortem (the CLI
        # acceptance criterion)
        from distlr_tpu import launch

        pm = os.path.join(doc["path"], "POSTMORTEM.md")
        os.remove(pm)
        assert launch.main(["incident", "render",
                            "--obs-run-dir", run]) == 0
        assert os.path.exists(pm)
