"""Regression tests for the lazy-stderr logging handler.

The original ``StreamHandler(sys.stderr)`` bound the stream object at
first-logger creation, so a logger created under one capture context kept
writing to that (stale) stream in every later context — the order-dependent
failure mode of ``test_keyed_ps_run_uses_vpk_and_converges`` under the full
suite (VERDICT r5 weak #4).  These tests run two capture contexts in
sequence and assert each sees exactly its own emissions.
"""

import contextlib
import io
import logging

from distlr_tpu.utils.logging import get_logger


def test_handler_follows_stderr_across_capture_contexts():
    # Create the logger INSIDE the first capture context — the original
    # bug froze the handler onto whatever sys.stderr was at this moment.
    buf1, buf2 = io.StringIO(), io.StringIO()
    with contextlib.redirect_stderr(buf1):
        log = get_logger("distlr_tpu.test_lazy_stream")
        log.warning("first-context line")
    with contextlib.redirect_stderr(buf2):
        log.warning("second-context line")
    assert "first-context line" in buf1.getvalue()
    assert "second-context line" not in buf1.getvalue()
    assert "second-context line" in buf2.getvalue()
    assert "first-context line" not in buf2.getvalue()


def test_existing_package_loggers_rebind(capfd):
    # Loggers created long ago (package import time) must also emit to the
    # CURRENT fd-2 stream — what capfd captures.
    log = get_logger("distlr_tpu.train.ps_trainer")
    capfd.readouterr()
    log.info("rebind probe line")
    assert "rebind probe line" in capfd.readouterr().err


def test_single_handler_per_logger():
    # get_logger must stay idempotent: repeated calls add no handlers.
    a = get_logger("distlr_tpu.test_idem")
    b = get_logger("distlr_tpu.test_idem")
    assert a is b
    assert len(a.handlers) == 1
    assert isinstance(a.handlers[0], logging.StreamHandler)
