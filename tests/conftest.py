"""Test config: simulate an 8-chip mesh on CPU.

Forces the CPU platform with 8 virtual devices so multi-chip
sharding/collective logic is exercised without TPU hardware — the JAX
equivalent of the reference faking a cluster with env vars in ``local.sh``
(SURVEY.md §4).  The environment may pre-import jax with a TPU platform
(sitecustomize), so this uses ``jax.config.update`` rather than env vars;
``XLA_FLAGS`` must still be set before the first backend initialization.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
