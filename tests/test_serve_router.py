"""Serving scale-out tests (ISSUE 4): the routing front-end + hot-row
keyed reload.

Covers the tentpole acceptance surface: load balancing and protocol
parity through the router, admission control (explicit ``ERR SHED`` +
counter consistency), the failover e2e — two REAL engine replicas over
TCP, one killed mid-load with zero failed accepted requests and the
ejected -> reinstated lifecycle visible in one fleet scrape via an
``--obs-run-dir`` — plus the hot-set tracker, the keyed hot-slice reload
(bytes-pulled < 10% of a full refresh at D=1M with identical served
scores), and the jittered reload polling regression.

All tests are CPU-only (tier-1: they run under ``-m 'not slow'``).
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

from distlr_tpu.config import Config
from distlr_tpu.obs.registry import get_registry
from distlr_tpu.serve import (
    HotReloader,
    HotSetTracker,
    LivePSWatcher,
    ScoringEngine,
    ScoringRouter,
    ScoringServer,
)
from distlr_tpu.serve.server import score_lines_over_tcp


def _sigmoid(z):
    return 1.0 / (1.0 + np.exp(-np.asarray(z, np.float64)))


def _mk_replica(port: int = 0) -> ScoringServer:
    cfg = Config(num_feature_dim=8, model="binary_lr", l2_c=0.0)
    eng = ScoringEngine(cfg, max_batch_size=64)
    eng.set_weights(np.linspace(-1.0, 1.0, 8).astype(np.float32))
    return ScoringServer(eng, port=port, max_wait_ms=0.5).start()


def _wait_for(predicate, timeout_s: float = 30.0, what: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


class TestHotSetTracker:
    def test_observe_publish_sorted(self):
        t = HotSetTracker(16)
        t.observe(np.array([9, 3, 3, 7], np.uint64))
        keys = t.hot_keys()
        assert keys.dtype == np.uint64
        assert keys.tolist() == [3, 7, 9]

    def test_capacity_keeps_top_counts(self):
        t = HotSetTracker(3)
        t.observe(np.array([1] * 5 + [2] * 4 + [3] * 3 + [4] * 2 + [5],
                           np.uint64))
        assert set(t.hot_keys().tolist()) == {1, 2, 3}
        assert t.evictions >= 2

    def test_decay_evicts_cold_keys(self):
        t = HotSetTracker(100, decay=0.5, decay_every=10, min_count=0.9)
        t.observe(np.array([1] * 9 + [2], np.uint64))  # triggers the decay
        assert t.decays == 1
        # key 1: 9 * 0.5 = 4.5 survives; key 2: 1 * 0.5 = 0.5 < 0.9 evicted
        assert t.hot_keys().tolist() == [1]

    def test_coverage_window(self):
        t = HotSetTracker(10)
        assert t.coverage() == 1.0          # no traffic: no drift evidence
        t.observe(np.array([1, 2, 3], np.uint64))
        assert t.coverage() == 0.0          # published snapshot still empty
        t.hot_keys()                        # publish {1, 2, 3}
        t.observe(np.array([1, 2], np.uint64))
        assert t.coverage() == 1.0
        t.observe(np.array([9, 9], np.uint64))
        assert t.coverage() == pytest.approx(0.5)
        t.hot_keys()                        # window resets
        assert t.coverage() == 1.0

    def test_empty_observe_and_empty_set(self):
        t = HotSetTracker(4)
        t.observe(np.array([], np.uint64))
        assert t.hot_keys().size == 0
        assert t.stats()["keys"] == 0

    def test_validation(self):
        with pytest.raises(ValueError, match="capacity"):
            HotSetTracker(0)
        with pytest.raises(ValueError, match="decay"):
            HotSetTracker(4, decay=0.0)
        with pytest.raises(ValueError, match="decay_every"):
            HotSetTracker(4, decay_every=0)


class TestRouterBasics:
    def test_balances_and_protocol_parity(self):
        a, b = _mk_replica(), _mk_replica()
        router = ScoringRouter(
            [f"{a.host}:{a.port}", f"{b.host}:{b.port}"],
            max_inflight=4, health_interval_s=5.0,
        ).start()
        try:
            w = np.linspace(-1.0, 1.0, 8)
            replies = score_lines_over_tcp(
                router.host, router.port, ["1:1 3:1"] * 8)
            assert all(not r.startswith("ERR") for r in replies)
            scores = {float(r.split()[1]) for r in replies}
            assert len(scores) == 1  # both replicas serve the same model
            np.testing.assert_allclose(
                scores.pop(), _sigmoid(w[0] + w[2]), atol=5e-3)
            # JSON batch mode passes through untouched
            (jrep,) = score_lines_over_tcp(
                router.host, router.port, [json.dumps({"rows": ["1:1", "2:1"]})])
            out = json.loads(jrep)
            assert len(out["labels"]) == 2 and len(out["scores"]) == 2
            # replica-level ERR (malformed input) is deterministic: it
            # passes through, is NOT retried, and ejects nobody
            (bad,) = score_lines_over_tcp(
                router.host, router.port, ['{"rows": []}'])
            assert bad.startswith("ERR") and "SHED" not in bad
            st = router.stats()
            assert st["errors"] == 0 and st["retries"] == 0
            assert st["replicas_up"] == 2
            # rotation spreads even strictly serial traffic
            per_rep = [r["requests"] for r in st["replicas"]]
            assert min(per_rep) >= 2, per_rep
        finally:
            router.stop()
            a.stop()
            b.stop()

    def test_rejects_ipv6_and_malformed_addresses_at_construction(self):
        for bad in ("[::1]:8101", "::1:8101", "127.0.0.1", "h:x"):
            with pytest.raises(ValueError):
                ScoringRouter([bad])

    def test_stats_schema_shared_with_server(self):
        """The router's STATS carries the front-end scalar schema (one
        parser for both tiers) plus the per-replica list."""
        a = _mk_replica()
        router = ScoringRouter([f"{a.host}:{a.port}"], max_inflight=2,
                               health_interval_s=5.0).start()
        try:
            score_lines_over_tcp(router.host, router.port, ["1:1"])
            (raw,) = score_lines_over_tcp(router.host, router.port, ["STATS"])
            st = json.loads(raw)
            assert set(st) == {"requests", "errors", "qps", "p50_ms",
                               "p99_ms", "shed", "retries", "replica_count",
                               "replicas_up", "replicas",
                               "models", "per_model"}
            assert st["requests"] == 1 and st["replica_count"] == 1
            # ISSUE-10 multi-tenant additions (additive): an old-style
            # replica list reads as one "default" model
            assert st["models"] == 1
            assert set(st["per_model"]) == {"default"}
            assert set(st["replicas"][0]) == {
                "addr", "healthy", "inflight", "requests", "errors",
                "ejections", "reinstates"}
        finally:
            router.stop()
            a.stop()

    def test_admission_shed_explicit_and_counted(self):
        """Saturating the per-replica in-flight budget sheds with an
        explicit ERR SHED reply — never a silent hang — and every shed
        reply is counted in distlr_route_shed_total."""
        cfg = Config(num_feature_dim=8, model="binary_lr", l2_c=0.0)
        eng = ScoringEngine(cfg, max_batch_size=64)
        eng.set_weights(np.ones(8, np.float32))
        orig_score = eng.score

        def slow_score(rows):
            time.sleep(0.25)
            return orig_score(rows)

        eng.score = slow_score  # bound before the server captures it
        srv = ScoringServer(eng, max_wait_ms=0.1).start()
        router = ScoringRouter([f"{srv.host}:{srv.port}"], max_inflight=1,
                               retries=0, health_interval_s=30.0).start()
        shed_family = get_registry().get("distlr_route_shed_total")
        shed_child = shed_family.labels(
            listener=f"{router.host}:{router.port}")
        base = shed_child.value
        try:
            n = 6
            replies: list[str] = []
            lock = threading.Lock()
            barrier = threading.Barrier(n)

            def one_request():
                barrier.wait()
                (r,) = score_lines_over_tcp(router.host, router.port, ["1:1"])
                with lock:
                    replies.append(r)

            threads = [threading.Thread(target=one_request) for _ in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert len(replies) == n  # every request ANSWERED, none hung
            shed = [r for r in replies if r.startswith("ERR SHED")]
            ok = [r for r in replies if not r.startswith("ERR")]
            assert len(shed) + len(ok) == n  # shed or served, nothing else
            assert len(shed) >= 1
            st = router.stats()
            assert st["shed"] == len(shed)
            assert st["requests"] == len(ok)
            assert st["errors"] == 0
            assert shed_child.value - base == len(shed)
        finally:
            router.stop()
            srv.stop()


class TestStalePooledConnection:
    def test_replica_restart_between_bursts_not_ejected(self):
        """A replica that restarted cleanly between traffic bursts
        leaves stale sockets in the router's pool; the failure belongs
        to the socket, not the replica — one fresh dial must recover it
        without burning the consecutive-error budget."""
        from distlr_tpu.serve.router import _Replica

        srv = _mk_replica()
        rep = _Replica(f"{srv.host}:{srv.port}", max_inflight=4,
                       timeout_s=5.0)
        assert not rep.exchange("1:1").startswith("ERR")
        assert len(rep._idle) == 1           # connection went back to pool
        port = srv.port
        srv.abort()                          # crash, pool entry now stale
        srv2 = _mk_replica(port=port)        # clean restart, same address
        try:
            reply = rep.exchange("1:1")      # pooled fails -> fresh dial
            assert not reply.startswith("ERR")
        finally:
            rep.drain_pool()
            srv2.stop()


class TestNestedShed:
    def test_child_shed_propagates_as_shed_not_outage(self):
        """A child tier answering ERR SHED is overloaded, not dead: the
        parent must propagate the shed (scale-up signal) without
        ejecting the child or ticking the error counter."""
        import socketserver as ss

        class _ShedHandler(ss.StreamRequestHandler):
            def handle(self):
                for _ in self.rfile:
                    self.wfile.write(
                        b"ERR SHED: no replica with free capacity\n")
                    self.wfile.flush()

        class _Srv(ss.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        fake_child = _Srv(("127.0.0.1", 0), _ShedHandler)
        threading.Thread(target=fake_child.serve_forever,
                         daemon=True).start()
        host, port = fake_child.server_address[:2]
        router = ScoringRouter([f"{host}:{port}"], max_inflight=4,
                               eject_after=1, health_interval_s=30.0,
                               probe_backoff_s=5.0, probe_backoff_max_s=10.0,
                               backend_timeout_s=5.0).start()
        try:
            (r1,) = score_lines_over_tcp(router.host, router.port, ["1:1"])
            assert r1.startswith("ERR SHED")
            st = router.stats()
            assert st["shed"] == 1 and st["errors"] == 0
            # overload is not death: no ejection from shed replies
            assert st["replicas"][0]["healthy"]
            assert st["replicas"][0]["ejections"] == 0
        finally:
            router.stop()
            fake_child.shutdown()
            fake_child.server_close()


class TestRouterOutage:
    def test_total_outage_is_error_not_shed(self):
        """Zero healthy replicas is an OUTAGE: the reply and the counter
        must say error (page someone), not shed (scale up)."""
        # grab a port that nothing listens on
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        dead_addr = "127.0.0.1:%d" % probe.getsockname()[1]
        probe.close()
        router = ScoringRouter([dead_addr], max_inflight=2, eject_after=1,
                               health_interval_s=30.0, probe_backoff_s=5.0,
                               probe_backoff_max_s=10.0,
                               backend_timeout_s=2.0).start()
        try:
            # first request: accepted (replica still in rotation), fails
            # on the dead address, ejects it -> ERR ROUTE + error count
            (r1,) = score_lines_over_tcp(router.host, router.port, ["1:1"])
            assert r1.startswith("ERR ROUTE")
            # second request: nothing healthy at admission — still an
            # outage error, NOT a shed
            (r2,) = score_lines_over_tcp(router.host, router.port, ["1:1"])
            assert r2.startswith("ERR ROUTE") and "no healthy replica" in r2
            st = router.stats()
            assert st["shed"] == 0
            assert st["errors"] == 2
            assert st["retries"] == 0  # nowhere to retry: not counted
            assert st["replicas_up"] == 0
        finally:
            router.stop()

    def test_stop_before_start_does_not_hang(self):
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        addr = "127.0.0.1:%d" % probe.getsockname()[1]
        probe.close()
        router = ScoringRouter([addr])
        t0 = time.monotonic()
        router.stop()  # never started: must return, not deadlock
        assert time.monotonic() - t0 < 5.0
        srv = _mk_replica()
        srv.stop()
        cfg = Config(num_feature_dim=8, model="binary_lr", l2_c=0.0)
        eng = ScoringEngine(cfg)
        eng.set_weights(np.zeros(8, np.float32))
        never_started = ScoringServer(eng)
        t0 = time.monotonic()
        never_started.stop()
        assert time.monotonic() - t0 < 5.0


class TestNestedRouter:
    def test_dead_child_tier_fails_over_and_stays_ejected(self):
        """A nested child router whose whole tier is down still answers
        STATS and replies ERR ROUTE — the parent must treat both as
        replica failure: retry the request on a sibling, eject the
        subtree, and NOT reinstate it off a bare STATS round trip."""
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        dead_addr = "127.0.0.1:%d" % probe.getsockname()[1]
        probe.close()
        child = ScoringRouter([dead_addr], eject_after=1,
                              health_interval_s=30.0, probe_backoff_s=5.0,
                              probe_backoff_max_s=10.0,
                              backend_timeout_s=2.0).start()
        srv = _mk_replica()
        parent = ScoringRouter(
            [f"{child.host}:{child.port}", f"{srv.host}:{srv.port}"],
            max_inflight=8, eject_after=2, health_interval_s=0.2,
            probe_backoff_s=0.1, probe_backoff_max_s=0.3,
            backend_timeout_s=5.0,
        ).start()
        child_addr = f"{child.host}:{child.port}"
        try:
            replies = score_lines_over_tcp(parent.host, parent.port,
                                           ["1:1 3:1"] * 10)
            # every accepted request answered with a score — the dead
            # subtree's ERR ROUTE replies were retried onto the engine
            assert not [r for r in replies if r.startswith("ERR")], replies

            def child_state():
                return next(r for r in parent.stats()["replicas"]
                            if r["addr"] == child_addr)
            _wait_for(lambda: not child_state()["healthy"],
                      what="child tier ejection")
            # probes DO reach the child's STATS, but replicas_up == 0
            # must keep it out of rotation (no reinstate flapping)
            time.sleep(1.0)
            assert not child_state()["healthy"]
            assert child_state()["reinstates"] == 0
        finally:
            parent.stop()
            child.stop()
            srv.stop()


class TestRouterFailover:
    """The ISSUE-4 acceptance e2e: two real engine replicas behind the
    router, one killed under live load — zero failed accepted requests,
    shed-counter consistency, and the ejected -> reinstated lifecycle
    visible in one fleet scrape via --obs-run-dir."""

    def test_kill_one_replica_under_load(self, tmp_path):
        from distlr_tpu.obs import FleetScraper, MetricsServer, write_endpoint

        a, b = _mk_replica(), _mk_replica()
        addr_b = f"{b.host}:{b.port}"
        router = ScoringRouter(
            [f"{a.host}:{a.port}", addr_b],
            max_inflight=32, eject_after=2, health_interval_s=0.2,
            probe_backoff_s=0.1, probe_backoff_max_s=0.5,
            backend_timeout_s=10.0,
        ).start()

        def rep_b_state():
            return next(r for r in router.stats()["replicas"]
                        if r["addr"] == addr_b)

        n_clients = 3
        replies: list[list[str]] = [[] for _ in range(n_clients)]
        client_errors: list[BaseException] = []
        stop = threading.Event()

        def client(i):
            try:
                with socket.create_connection(
                        (router.host, router.port), timeout=30) as s:
                    f = s.makefile("rwb")
                    while not stop.is_set():
                        f.write(b"1:1 3:1\n")
                        f.flush()
                        r = f.readline()
                        if not r:
                            raise ConnectionError("router closed mid-stream")
                        replies[i].append(r.decode().strip())
            except BaseException as e:  # surfaced below
                client_errors.append(e)

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(n_clients)]
        b2 = None
        try:
            for t in threads:
                t.start()
            _wait_for(lambda: sum(len(r) for r in replies) > 50,
                      what="load ramp")
            # KILL replica b mid-load: sever the listener and every
            # active connection, exactly like a SIGKILL
            b.abort()
            _wait_for(lambda: not rep_b_state()["healthy"],
                      what="replica b ejection")
            # load continues against the survivor while b is down
            n_at_eject = sum(len(r) for r in replies)
            _wait_for(lambda: sum(len(r) for r in replies) > n_at_eject + 30,
                      what="post-ejection load")
            # respawn a replica on the SAME address; backoff probes
            # reinstate it without a router restart
            b2 = _mk_replica(port=b.port)
            _wait_for(lambda: rep_b_state()["healthy"],
                      what="replica b reinstatement")
            n_at_reinstate = sum(len(r) for r in replies)
            _wait_for(
                lambda: sum(len(r) for r in replies) > n_at_reinstate + 30,
                what="post-reinstatement load")
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        try:
            assert not client_errors, client_errors
            flat = [r for per in replies for r in per]
            assert flat
            # 100% of ACCEPTED requests answered with a score: the kill
            # surfaced as transparent retries, never as a failed reply
            failed = [r for r in flat if r.startswith("ERR")]
            assert failed == [], failed[:5]
            st = router.stats()
            assert st["shed"] == 0 and st["errors"] == 0
            assert st["retries"] >= 1  # in-flight victims were retried
            rb = rep_b_state()
            assert rb["ejections"] >= 1 and rb["reinstates"] >= 1
            assert st["replicas_up"] == 2

            # ...and the whole lifecycle is visible in ONE fleet scrape:
            # publish this process's registry as the route rank of a run
            # dir and federate it, the way `launch route --obs-run-dir`
            # + `launch obs-agg` do across processes.
            run = str(tmp_path)
            msrv = MetricsServer(registry=get_registry(), port=0).start()
            try:
                write_endpoint(run, "route", 0, msrv.host, msrv.port)
                fs = FleetScraper(run, interval_s=0.2)
                fs.scrape_once()
                text = fs.prometheus_text()
            finally:
                msrv.stop()
            assert f'distlr_route_ejections_total{{replica="{addr_b}"}}' \
                in text
            assert f'distlr_route_reinstates_total{{replica="{addr_b}"}}' \
                in text
            assert ('distlr_route_replica_up{role="route",rank="0",'
                    f'replica="{addr_b}"}} 1') in text
            assert "distlr_route_shed_total" in text
            assert "distlr_route_request_seconds_bucket" in text
            fleet = fs.fleet_json()
            route_rows = [r for r in fleet["ranks"] if r["role"] == "route"]
            # the registry is process-wide, so other tests' routers also
            # contribute children — assert presence and a sane floor,
            # not exact equality
            assert route_rows
            assert route_rows[0]["replicas_up"] >= 2
            assert route_rows[0]["route_requests"] >= len(flat)
            assert "route_shed" in route_rows[0]
        finally:
            router.stop()
            a.stop()
            if b2 is not None:
                b2.stop()


@pytest.fixture()
def ps_group_1m():
    from distlr_tpu.ps import KVWorker, ServerGroup

    dim = 1_000_000
    with ServerGroup(2, 1, dim=dim, sync=False) as sg, \
            KVWorker(sg.hosts, dim, client_id=7) as kv:
        yield sg, kv, dim


def _pull_bytes() -> float:
    fam = get_registry().get("distlr_ps_client_bytes_total")
    if fam is None:
        return 0.0
    return sum(child.value for values, child in fam.children()
               if values[0] == "pull")


class TestHotRowReload:
    def test_bytes_and_identical_scores_at_1m(self, ps_group_1m):
        """ISSUE-4 acceptance: D=1M, concentrated key distribution —
        a hot-set refresh moves < 10% of a full refresh's bytes-pulled
        counter, and the served scores are identical to a full-table
        engine's."""
        sg, kv, dim = ps_group_1m
        rng = np.random.default_rng(21)
        w0 = (rng.standard_normal(dim) * 0.5).astype(np.float32)
        kv.wait(kv.push_init(w0))

        cfg = Config(num_feature_dim=dim, model="sparse_lr", l2_c=0.0)
        eng = ScoringEngine(cfg, max_batch_size=128)
        tracker = HotSetTracker(1024)
        watcher = LivePSWatcher(sg.hosts, dim, hot_tracker=tracker,
                                min_coverage=0.9, full_refresh_every=0)
        try:
            # the concentrated working set: every request draws from
            # these 200 keys out of 1M
            pool = np.sort(rng.choice(dim, size=200, replace=False))
            lines = []
            for _ in range(40):
                cols = np.sort(rng.choice(pool, size=5, replace=False))
                lines.append(" ".join(f"{c + 1}:1" for c in cols))

            v, w = watcher.poll()          # first poll: full (no table)
            eng.set_weights(w)
            with ScoringServer(eng, max_wait_ms=0.5,
                               hot_tracker=tracker) as srv:
                replies0 = score_lines_over_tcp(srv.host, srv.port, lines)
                # traffic arrived after the first publish: coverage is
                # low, so the next poll falls back to a FULL refresh and
                # publishes the now-populated hot set
                t0 = _pull_bytes()
                _, w = watcher.poll()
                bytes_full = _pull_bytes() - t0
                assert watcher.last_kind == "full"
                eng.set_weights(w)
                replies1 = score_lines_over_tcp(srv.host, srv.port, lines)
                assert replies1 == replies0  # weights unchanged so far

                # the trainer moves the table; the hot slice tracks it
                w1 = (rng.standard_normal(dim) * 0.5).astype(np.float32)
                kv.wait(kv.push_init(w1, force=True))
                t0 = _pull_bytes()
                _, w = watcher.poll()
                bytes_hot = _pull_bytes() - t0
                assert watcher.last_kind == "hot"
                assert watcher.last_rows <= 1024
                assert bytes_full > 0 and bytes_hot > 0
                # the headline acceptance number
                assert bytes_hot < 0.10 * bytes_full, (bytes_hot, bytes_full)
                eng.set_weights(w)
                replies2 = score_lines_over_tcp(srv.host, srv.port, lines)

            # identical scores: a second engine loaded with the FULL new
            # table scores the same requests; the hot-reloaded engine
            # must agree bit-for-bit (requests only touch hot rows)
            eng_full = ScoringEngine(cfg, max_batch_size=128)
            eng_full.set_weights(kv.pull_chunked())
            labels, scores = eng_full.score(eng_full.encode_lines(lines))
            expect = [f"{int(l)} {float(s):.6g}"
                      for l, s in zip(labels, scores)]
            assert replies2 == expect
            assert watcher.stats()["full_reloads"] == 2
            assert watcher.stats()["hot_reloads"] == 1
            assert watcher.stats()["hot_set"]["keys"] <= 1024
        finally:
            watcher.close()

    def test_coverage_fallback_forces_full(self):
        from distlr_tpu.ps import KVWorker, ServerGroup

        with ServerGroup(1, 1, dim=64, sync=False) as sg, \
                KVWorker(sg.hosts, 64, client_id=8) as kv:
            kv.wait(kv.push_init(np.arange(64, dtype=np.float32)))
            tracker = HotSetTracker(32)
            watcher = LivePSWatcher(sg.hosts, 64, hot_tracker=tracker,
                                    min_coverage=0.95, full_refresh_every=0)
            try:
                kinds = []
                watcher.poll()                       # table bootstrap
                kinds.append(watcher.last_kind)
                tracker.observe(np.array([5, 6, 7], np.uint64))
                watcher.poll()                       # coverage 0 -> full
                kinds.append(watcher.last_kind)
                tracker.observe(np.array([5, 6], np.uint64))
                watcher.poll()                       # covered -> hot
                kinds.append(watcher.last_kind)
                # the distribution shifts: mostly-new keys, coverage dives
                tracker.observe(np.array([50] * 10 + [5], np.uint64))
                watcher.poll()
                kinds.append(watcher.last_kind)
                assert kinds == ["full", "full", "hot", "full"]
            finally:
                watcher.close()

    def test_poll_result_never_aliases_cached_table(self):
        """The engine device_puts what poll() returns, and device_put of
        an aligned float32 array can be zero-copy — later in-place hot
        patches must not reach weights already handed out."""
        from distlr_tpu.ps import KVWorker, ServerGroup

        with ServerGroup(1, 1, dim=16, sync=False) as sg, \
                KVWorker(sg.hosts, 16, client_id=13) as kv:
            kv.wait(kv.push_init(np.zeros(16, np.float32)))
            tracker = HotSetTracker(8)
            watcher = LivePSWatcher(sg.hosts, 16, hot_tracker=tracker,
                                    min_coverage=0.5, full_refresh_every=0)
            try:
                watcher.poll()
                tracker.observe(np.array([3], np.uint64))
                _, w1 = watcher.poll()
                assert not np.shares_memory(w1, watcher._table)
                before = w1.copy()
                kv.wait(kv.push_init(np.full(16, 9.0, np.float32),
                                     force=True))
                tracker.observe(np.array([3], np.uint64))
                watcher.poll()  # patches the cached table in place
                np.testing.assert_array_equal(w1, before)
            finally:
                watcher.close()

    def test_idle_hot_poll_is_noop(self):
        """An idle replica (empty hot set, table already published) must
        not report a new version every poll — that would re-upload an
        identical D-dim table to the device once per interval."""
        from distlr_tpu.ps import KVWorker, ServerGroup

        with ServerGroup(1, 1, dim=16, sync=False) as sg, \
                KVWorker(sg.hosts, 16, client_id=12) as kv:
            kv.wait(kv.push_init(np.ones(16, np.float32)))
            watcher = LivePSWatcher(sg.hosts, 16,
                                    hot_tracker=HotSetTracker(4))
            try:
                assert watcher.poll() is not None   # bootstrap full pull
                assert watcher.poll() is None       # no traffic: no-op
                assert watcher.poll() is None
                assert watcher.hot_reloads == 0
            finally:
                watcher.close()

    def test_periodic_full_refresh_bounds_staleness(self):
        from distlr_tpu.ps import KVWorker, ServerGroup

        with ServerGroup(1, 1, dim=32, sync=False) as sg, \
                KVWorker(sg.hosts, 32, client_id=9) as kv:
            kv.wait(kv.push_init(np.zeros(32, np.float32)))
            tracker = HotSetTracker(8)
            watcher = LivePSWatcher(sg.hosts, 32, hot_tracker=tracker,
                                    min_coverage=0.5, full_refresh_every=2)
            try:
                watcher.poll()                              # full (bootstrap)
                tracker.observe(np.array([1, 2], np.uint64))
                watcher.poll()                              # full (coverage)
                kinds = []
                for _ in range(5):
                    tracker.observe(np.array([1, 2], np.uint64))
                    watcher.poll()
                    kinds.append(watcher.last_kind)
                # every 3rd poll goes full even though coverage stays 1.0
                assert kinds == ["hot", "hot", "full", "hot", "hot"]
            finally:
                watcher.close()

    def test_pull_rows_into_scatters_in_place(self):
        from distlr_tpu.ps import KVWorker, ServerGroup

        with ServerGroup(2, 1, dim=48, sync=False) as sg, \
                KVWorker(sg.hosts, 48, client_id=10) as kv:
            init = np.linspace(-2, 2, 48).astype(np.float32)
            kv.wait(kv.push_init(init))
            assert kv.supports_vals_per_key(4)
            table = np.zeros(48, np.float32)
            rows = np.array([1, 5, 9], np.uint64)
            n = kv.pull_rows_into(table, rows, vals_per_key=4, chunk_rows=2)
            assert n == 3
            t = table.reshape(12, 4)
            for r in (1, 5, 9):
                np.testing.assert_allclose(
                    t[r], init.reshape(12, 4)[r])
            untouched = [r for r in range(12) if r not in (1, 5, 9)]
            assert np.all(t[untouched] == 0.0)
            # empty key set is a no-op, not a crash
            assert kv.pull_rows_into(table, np.array([], np.uint64)) == 0
            with pytest.raises(ValueError, match="C-contiguous float32"):
                kv.pull_rows_into(np.zeros(5, np.float32), rows)

    def test_serve_row_width_matches_row_keys_space(self):
        """The launcher's PS row width must match the key space
        ScoringEngine.row_keys feeds the tracker — DENSE softmax also
        owns num_classes flat slots per feature key (ps_param_dim
        flattens the (D, K) matrix row-major)."""
        from distlr_tpu.launch import _serve_row_width

        assert _serve_row_width(Config(model="binary_lr")) == 1
        assert _serve_row_width(Config(model="sparse_lr")) == 1
        assert _serve_row_width(
            Config(model="softmax", num_classes=4)) == 4
        assert _serve_row_width(
            Config(model="sparse_softmax", num_classes=3)) == 3
        assert _serve_row_width(
            Config(model="blocked_lr", block_size=8)) == 8

    def test_dense_softmax_hot_reload_patches_class_rows(self):
        """Dense softmax over the PS: feature key j owns flat slots
        [j*K, (j+1)*K) — a hot refresh of feature rows must patch whole
        K-wide rows, not K unrelated flat slots."""
        from distlr_tpu.ps import KVWorker, ServerGroup

        with ServerGroup(1, 1, dim=36, sync=False) as sg, \
                KVWorker(sg.hosts, 36, client_id=14) as kv:
            init = np.arange(36, dtype=np.float32)
            kv.wait(kv.push_init(init))
            tracker = HotSetTracker(8)
            watcher = LivePSWatcher(sg.hosts, 36, vals_per_key=3,
                                    hot_tracker=tracker, min_coverage=0.5,
                                    full_refresh_every=0)
            try:
                assert watcher.row_width == 3
                watcher.poll()                              # bootstrap
                tracker.observe(np.array([2, 7], np.uint64))
                watcher.poll()                              # coverage full
                kv.wait(kv.push_init(init + 100.0, force=True))
                tracker.observe(np.array([2, 7], np.uint64))
                _, w = watcher.poll()
                assert watcher.last_kind == "hot"
                t = np.asarray(w).reshape(12, 3)
                np.testing.assert_allclose(t[2], init.reshape(12, 3)[2] + 100)
                np.testing.assert_allclose(t[7], init.reshape(12, 3)[7] + 100)
                np.testing.assert_allclose(t[3], init.reshape(12, 3)[3])
            finally:
                watcher.close()

    def test_vpk_fallback_expands_row_keys(self):
        """A server group whose range boundaries straddle R-lane rows
        falls back to flat keys; hot row ids must expand to their R flat
        slots so the patched table stays row-aligned."""
        from distlr_tpu.ps import KVWorker, ServerGroup

        with ServerGroup(3, 1, dim=50, sync=False) as sg, \
                KVWorker(sg.hosts, 50, client_id=11) as kv:
            assert not kv.supports_vals_per_key(5)
            init = np.arange(50, dtype=np.float32)
            kv.wait(kv.push_init(init))
            tracker = HotSetTracker(8)
            watcher = LivePSWatcher(sg.hosts, 50, vals_per_key=5,
                                    hot_tracker=tracker, min_coverage=0.5,
                                    full_refresh_every=0)
            try:
                assert watcher.vals_per_key == 1 and watcher.row_width == 5
                watcher.poll()                              # bootstrap
                tracker.observe(np.array([2, 7], np.uint64))
                watcher.poll()                              # coverage full
                # move the whole table; only rows 2 and 7 may refresh
                kv.wait(kv.push_init(init + 100.0, force=True))
                tracker.observe(np.array([2, 7], np.uint64))
                _, w = watcher.poll()
                assert watcher.last_kind == "hot"
                t = np.asarray(w).reshape(10, 5)
                np.testing.assert_allclose(t[2], init.reshape(10, 5)[2] + 100)
                np.testing.assert_allclose(t[7], init.reshape(10, 5)[7] + 100)
                np.testing.assert_allclose(t[3], init.reshape(10, 5)[3])
            finally:
                watcher.close()


class TestReloadJitter:
    """Satellite (ISSUE 4 bugfix): fixed-interval polling puts N
    replicas started together in lockstep against the PS forever —
    waits are now jittered per reloader."""

    def test_jitter_bounds_and_variation(self):
        r = HotReloader(None, None, interval_s=0.1)
        waits = [r._next_wait() for _ in range(200)]
        assert all(0.1 * 0.8 <= w <= 0.1 * 1.2 for w in waits)
        assert len(set(waits)) > 10  # actually random, not a fixed offset

    def test_two_reloaders_desynchronize(self):
        r1 = HotReloader(None, None, interval_s=0.1)
        r2 = HotReloader(None, None, interval_s=0.1)
        s1 = [r1._next_wait() for _ in range(20)]
        s2 = [r2._next_wait() for _ in range(20)]
        # independently-seeded RNGs: two replicas launched in the same
        # millisecond draw different wait sequences and drift apart
        assert s1 != s2
        assert abs(sum(s1) - sum(s2)) > 0.0

    def test_jitter_zero_restores_fixed_cadence(self):
        r = HotReloader(None, None, interval_s=0.5, jitter=0.0)
        assert {r._next_wait() for _ in range(5)} == {0.5}

    def test_jitter_validation(self):
        with pytest.raises(ValueError, match="jitter"):
            HotReloader(None, None, interval_s=1.0, jitter=1.0)
        with pytest.raises(ValueError, match="jitter"):
            HotReloader(None, None, interval_s=1.0, jitter=-0.1)
