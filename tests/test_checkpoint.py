import numpy as np
import pytest

from distlr_tpu.config import Config
from distlr_tpu.data.synthetic import write_synthetic_shards
from distlr_tpu.parallel import make_mesh
from distlr_tpu.train import Trainer
from distlr_tpu.train.checkpoint import Checkpointer


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("ckptdata")
    write_synthetic_shards(str(d), 800, 24, num_parts=4, seed=2, sparsity=0.0)
    return str(d)


class TestCheckpointer:
    def test_save_restore_roundtrip(self, tmp_path):
        with Checkpointer(str(tmp_path / "ck")) as ck:
            w = np.random.default_rng(0).standard_normal(10).astype(np.float32)
            ck.save(5, w, extra={"epoch": 5})
            assert ck.latest_step() == 5
            state = ck.restore()
            np.testing.assert_array_equal(state["weights"], w)
            assert int(state["epoch"]) == 5

    def test_restore_empty_returns_none(self, tmp_path):
        with Checkpointer(str(tmp_path / "empty")) as ck:
            assert ck.restore() is None

    def test_max_to_keep(self, tmp_path):
        with Checkpointer(str(tmp_path / "gc"), max_to_keep=2) as ck:
            for s in (1, 2, 3, 4):
                ck.save(s, np.zeros(3, np.float32), extra={"epoch": s})
            assert ck.all_steps() == [3, 4]


class TestTrainerResume:
    def test_resume_continues_training(self, data_dir, tmp_path):
        ck_dir = str(tmp_path / "run_ck")
        common = dict(
            data_dir=data_dir, num_feature_dim=24, learning_rate=0.5, l2_c=0.0,
            test_interval=0, checkpoint_dir=ck_dir, checkpoint_interval=5,
        )
        mesh = make_mesh({"data": 4})

        # full run: 20 epochs straight through
        cfg_full = Config(num_iteration=20, **common)
        tr_full = Trainer(cfg_full, mesh=mesh).load_data()
        w_full = np.asarray(tr_full.fit())

        # interrupted run: 10 epochs, then resume to 20 in a new Trainer
        ck2 = str(tmp_path / "run_ck2")
        common2 = {**common, "checkpoint_dir": ck2}
        tr_a = Trainer(Config(num_iteration=10, **common2), mesh=mesh).load_data()
        tr_a.fit()
        tr_b = Trainer(Config(num_iteration=20, **common2), mesh=mesh).load_data()
        w_resumed = np.asarray(tr_b.fit(resume=True))

        # deterministic data + deterministic init => identical trajectories
        np.testing.assert_allclose(w_resumed, w_full, atol=1e-5)

    def test_resume_with_no_checkpoint_starts_fresh(self, data_dir, tmp_path):
        cfg = Config(
            data_dir=data_dir, num_feature_dim=24, num_iteration=3,
            test_interval=0, checkpoint_dir=str(tmp_path / "fresh"),
            checkpoint_interval=0,
        )
        tr = Trainer(cfg, mesh=make_mesh({"data": 4})).load_data()
        w = tr.fit(resume=True)
        assert np.isfinite(np.asarray(w)).all()

    def test_final_checkpoint_written(self, data_dir, tmp_path):
        ck_dir = str(tmp_path / "final_ck")
        cfg = Config(
            data_dir=data_dir, num_feature_dim=24, num_iteration=7,
            test_interval=0, checkpoint_dir=ck_dir, checkpoint_interval=5,
        )
        Trainer(cfg, mesh=make_mesh({"data": 4})).load_data().fit()
        with Checkpointer(ck_dir) as ck:
            assert ck.latest_step() == 7
            assert 5 in ck.all_steps()

    def test_blocked_family_resume_matches_uninterrupted(self, tmp_path):
        """Resume is family-agnostic (the checkpoint carries the weight
        PYTREE — the blocked table is a (rows, R) array, not a vector);
        pin it with the same interrupted-vs-straight identity the dense
        family has."""
        from distlr_tpu.data.hashing import write_raw_ctr_shards

        d = str(tmp_path / "rawctr")
        write_raw_ctr_shards(d, 1600, 6, 4, 4, seed=11)
        common = dict(
            data_dir=d, num_feature_dim=1024, model="blocked_lr",
            block_size=4, learning_rate=0.5, l2_c=0.0, test_interval=0,
            checkpoint_interval=3,
        )
        mesh = make_mesh({"data": 4})

        ck_full = str(tmp_path / "bk_full")
        cfg_full = Config(num_iteration=10, checkpoint_dir=ck_full, **common)
        t_full = np.asarray(Trainer(cfg_full, mesh=mesh).load_data().fit())

        ck2 = str(tmp_path / "bk_resume")
        tr_a = Trainer(Config(num_iteration=5, checkpoint_dir=ck2, **common),
                       mesh=mesh).load_data()
        tr_a.fit()
        tr_b = Trainer(Config(num_iteration=10, checkpoint_dir=ck2, **common),
                       mesh=mesh).load_data()
        t_resumed = np.asarray(tr_b.fit(resume=True))

        assert t_resumed.shape == (256, 4)  # table, not flat vector
        np.testing.assert_allclose(t_resumed, t_full, atol=1e-5)

    def test_ps_blocked_resume_matches_straight_run(self, tmp_path):
        """PS-mode resume for the blocked family (keyed rows over the
        KV plane): interrupted-then-resumed equals straight-through,
        same as the dense PS resume identity."""
        from distlr_tpu.data.hashing import write_raw_ctr_shards
        from distlr_tpu.train.ps_trainer import run_ps_local

        d = str(tmp_path / "psraw")
        write_raw_ctr_shards(d, 1200, 6, 4, 2, seed=13)
        common = dict(
            data_dir=d, num_feature_dim=512, model="blocked_lr",
            block_size=4, learning_rate=0.5, l2_c=0.0, test_interval=0,
            num_workers=2, num_servers=2, batch_size=-1, sync_mode=True,
            checkpoint_interval=2,
        )
        ck1 = str(tmp_path / "ps_full")
        straight = run_ps_local(
            Config(num_iteration=6, checkpoint_dir=ck1, **common), save=False)

        ck2 = str(tmp_path / "ps_resume")
        run_ps_local(Config(num_iteration=3, checkpoint_dir=ck2, **common),
                     save=False)
        resumed = run_ps_local(
            Config(num_iteration=6, checkpoint_dir=ck2, **common),
            save=False, resume=True)
        np.testing.assert_allclose(resumed[0], straight[0],
                                   rtol=1e-5, atol=1e-6)
