"""Metrics-reference lint (ISSUE 8 satellite): ``docs/METRICS.md`` must
name every ``distlr_*`` series the code can emit, and must not carry
stale entries — the drift guard for a namespace that has grown every PR.
"""

import os

from distlr_tpu.obs import metrics_doc


class TestMetricsDoc:
    def test_doc_exists(self):
        assert os.path.exists(metrics_doc.doc_path()), (
            "docs/METRICS.md missing — run "
            "`python -m distlr_tpu.obs.metrics_doc`")

    def test_no_drift_between_code_and_doc(self):
        problems = metrics_doc.check()
        assert not problems, (
            "metric namespace drift (regenerate with `python -m "
            "distlr_tpu.obs.metrics_doc`):\n" + "\n".join(problems))

    def test_scan_sees_known_series(self):
        """The static scan must actually find the long-lived families —
        an over-eager filter passing test_no_drift vacuously would be
        worse than no lint."""
        names = {r.name for r in metrics_doc.collect_registrations()}
        for expected in (
            "distlr_ps_client_ops_total",
            "distlr_train_staleness_pushes",
            "distlr_serve_request_seconds",
            "distlr_route_requests_total",
            "distlr_feedback_joined_total",
            "distlr_chaos_faults_total",
            "distlr_trace_spans_total",
        ):
            assert expected in names, expected

    def test_doc_table_carries_help_text(self):
        with open(metrics_doc.doc_path()) as f:
            text = f.read()
        # one concrete row sanity-checks the rendering end of the
        # generator (name + kind + meaning columns intact)
        assert "`distlr_ps_retries_total` | counter" in text
