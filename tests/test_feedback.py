"""Online learning from served traffic (ISSUE 6): spool, delayed-label
join, drift detection, the continuous trainer, and the closed-loop
end-to-end — serve → label → join → online trainer → live PS → hot
reload → served scores move.

The e2e acceptance (short tier-1 variant here, slow chaos soak marked
``slow``): labels flip mid-run, served scores measurably track the new
label distribution within the same process lifetimes (zero restarts),
``distlr_alert_score_drift`` fires during the shift and clears after
adaptation, the FTRL server mode does the learning, and the loop's PS
legs cross the chaos proxy under a scripted fault plan.
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from distlr_tpu.config import Config
from distlr_tpu.feedback import (
    FeedbackSink,
    FeedbackSpool,
    LabelJoiner,
    OnlineTrainer,
    ScoreDriftDetector,
    SpoolRecord,
    per_row_keys,
    strip_label,
)
from distlr_tpu.ps import KVWorker, ServerGroup

D = 16


def _rec(rid, ts, line="1:1 2:1", score=0.5, keys=None):
    return SpoolRecord(rid=rid, ts=ts, line=line, score=score, version=1,
                       keys=None if keys is None
                       else np.asarray(keys, np.uint64))


# ---------------------------------------------------------------------------
# spool
# ---------------------------------------------------------------------------

class _Tracker:
    """HotSetTracker stand-in: importance = how many keys are 'hot'."""

    def __init__(self, hot):
        self.hot = set(hot)

    def importance(self, keys):
        return float(sum(1 for k in np.asarray(keys).reshape(-1)
                         if int(k) in self.hot))


class TestFeedbackSpool:
    def test_capacity_eviction_is_importance_aware(self, tmp_path):
        spool = FeedbackSpool(str(tmp_path), capacity=3,
                              tracker=_Tracker({7}), evict_scan=3)
        spool.add(_rec("cold-0", 1.0, keys=[1]))
        spool.add(_rec("hot", 2.0, keys=[7]))
        spool.add(_rec("cold-1", 3.0, keys=[2]))
        spool.add(_rec("cold-2", 4.0, keys=[3]))  # over capacity
        assert len(spool) == 3
        # the HOT record survives even though it is older than cold-1/2
        assert spool.pop("hot") is not None
        assert spool.pop("cold-0") is None  # the cold oldest was shed
        assert spool.evicted == 1

    def test_fifo_without_tracker(self, tmp_path):
        spool = FeedbackSpool(str(tmp_path), capacity=2)
        for i in range(4):
            spool.add(_rec(f"r{i}", float(i)))
        assert len(spool) == 2
        assert spool.pop("r0") is None and spool.pop("r1") is None
        assert spool.pop("r3") is not None

    def test_expire_before_returns_old_records(self, tmp_path):
        spool = FeedbackSpool(str(tmp_path), capacity=10)
        for i in range(5):
            spool.add(_rec(f"r{i}", float(i)))
        expired = spool.expire_before(3.0)
        assert [r.rid for r in expired] == ["r0", "r1", "r2"]
        assert len(spool) == 2

    def test_journal_is_bounded_on_disk(self, tmp_path):
        spool = FeedbackSpool(str(tmp_path), capacity=1000,
                              segment_records=5, max_segments=2)
        for i in range(23):
            spool.add(_rec(f"r{i}", float(i)))
        spool.close()
        segs = sorted(p for p in os.listdir(tmp_path)
                      if p.startswith("spool-"))
        assert len(segs) <= 2  # oldest segments deleted — bounded spool
        # the newest journal lines are valid JSON with the record fields
        with open(tmp_path / segs[-1]) as f:
            doc = json.loads(f.readline())
        assert {"id", "ts", "line", "score", "version"} <= set(doc)

    def test_importance_many_matches_per_record_path(self, tmp_path):
        """The real tracker's batched importance (one lock acquisition
        per eviction) ranks candidates exactly like the per-record
        fallback the _Tracker stand-in exercises."""
        from distlr_tpu.serve.hotset import HotSetTracker

        tracker = HotSetTracker(16)
        for _ in range(5):
            tracker.observe([7])
        spool = FeedbackSpool(str(tmp_path), capacity=3, tracker=tracker,
                              evict_scan=3)
        spool.add(_rec("cold-0", 1.0, keys=[1]))
        spool.add(_rec("hot", 2.0, keys=[7]))
        spool.add(_rec("cold-1", 3.0, keys=[2]))
        spool.add(_rec("cold-2", 4.0, keys=[3]))  # over capacity
        assert spool.pop("hot") is not None
        assert spool.pop("cold-0") is None
        assert tracker.importance_many([[7], [1], None, []]) == \
            [tracker.importance([7]), tracker.importance([1]), 0.0, 0.0]

    def test_journal_segments_resume_across_restart(self, tmp_path):
        """A restarted spool opens a FRESH segment past the old run's
        (no mixing) and re-enforces the max_segments disk bound over
        what the old run left behind."""
        spool = FeedbackSpool(str(tmp_path), capacity=1000,
                              segment_records=5, max_segments=2)
        for i in range(23):
            spool.add(_rec(f"r{i}", float(i)))
        spool.close()
        before = sorted(p for p in os.listdir(tmp_path)
                        if p.startswith("spool-"))
        spool2 = FeedbackSpool(str(tmp_path), capacity=1000,
                               segment_records=5, max_segments=2)
        spool2.add(_rec("next-run", 99.0))
        spool2.close()
        segs = sorted(p for p in os.listdir(tmp_path)
                      if p.startswith("spool-"))
        assert len(segs) <= 2  # bound holds across the restart
        assert segs[-1] not in before  # fresh segment, no mixed runs
        with open(tmp_path / segs[-1]) as f:
            assert json.loads(f.readline())["id"] == "next-run"

    def test_per_row_keys_and_strip_label(self):
        X = np.zeros((2, 6), np.float32)
        X[0, [1, 4]] = 1.0
        X[1, 2] = 2.0
        keys = per_row_keys("binary_lr", (X,))
        assert keys[0].tolist() == [1, 4] and keys[1].tolist() == [2]
        cols = np.array([[3, 5], [1, 1]])
        skeys = per_row_keys("sparse_lr", (cols, np.ones_like(cols)))
        assert skeys[0].tolist() == [3, 5] and skeys[1].tolist() == [1]
        assert strip_label("1 3:1 4:2") == "3:1 4:2"
        assert strip_label("3:1 4:2") == "3:1 4:2"
        assert strip_label("0.5 1:2") == "1:2"


# ---------------------------------------------------------------------------
# joiner
# ---------------------------------------------------------------------------

class TestLabelJoiner:
    def _mk(self, tmp_path, **kw):
        spool = FeedbackSpool(str(tmp_path / "spool"), capacity=100)
        kw.setdefault("window_s", 10.0)
        kw.setdefault("shard_records", 3)
        j = LabelJoiner(spool, str(tmp_path / "shards"), **kw)
        return spool, j

    def _shards(self, j):
        return sorted(p for p in os.listdir(j.out_dir)
                      if p.endswith(".libsvm"))

    def test_join_emits_labeled_lines(self, tmp_path):
        _, j = self._mk(tmp_path)
        for i in range(3):
            j.scored(_rec(f"r{i}", float(i), line=f"{i + 1}:1"))
        assert j.label("r0", 1, ts=5.0) == "joined"
        assert j.label("r1", 0, ts=5.0) == "joined"
        assert j.label("r2", 1, ts=5.0) == "joined"
        shards = self._shards(j)
        assert len(shards) == 1  # shard_records=3 filled exactly once
        with open(os.path.join(j.out_dir, shards[0])) as f:
            assert f.read().splitlines() == ["1 1:1", "0 2:1", "1 3:1"]

    def test_label_before_request_joins_on_arrival(self, tmp_path):
        _, j = self._mk(tmp_path)
        assert j.label("early", 1, ts=1.0) == "pending"
        j.scored(_rec("early", 2.0, line="9:1"))
        assert j.joined == 1
        j.flush()
        with open(os.path.join(j.out_dir, self._shards(j)[0])) as f:
            assert f.read().splitlines() == ["1 9:1"]

    def test_duplicate_labels_counted_not_reemitted(self, tmp_path):
        _, j = self._mk(tmp_path)
        j.scored(_rec("r", 1.0))
        assert j.label("r", 1, ts=2.0) == "joined"
        assert j.label("r", 0, ts=2.5) == "duplicate"
        assert j.joined == 1

    def test_expired_window_negative_sampling(self, tmp_path):
        _, j = self._mk(tmp_path, window_s=5.0, negative_rate=1.0)
        j.scored(_rec("old", 0.0, line="2:1"))
        j.scored(_rec("fresh", 8.0, line="3:1"))
        j.tick(now=6.0)  # only "old" is past the window
        assert j.negatives == 1
        j.flush()
        with open(os.path.join(j.out_dir, self._shards(j)[0])) as f:
            assert f.read().splitlines() == ["0 2:1"]
        # the fresh record is still joinable
        assert j.label("fresh", 1, ts=9.0) == "joined"

    def test_expired_window_drop_when_rate_zero(self, tmp_path):
        spool, j = self._mk(tmp_path, window_s=5.0, negative_rate=0.0)
        j.scored(_rec("old", 0.0))
        j.tick(now=6.0)
        assert j.negatives == 0 and len(spool) == 0
        # a late label for the expired request no longer joins
        assert j.label("old", 1, ts=7.0) == "duplicate"

    def test_unmatched_labels_expire(self, tmp_path):
        _, j = self._mk(tmp_path, window_s=5.0)
        assert j.label("ghost", 1, ts=0.0) == "pending"
        j.tick(now=6.0)
        assert j.stats()["pending_labels"] == 0

    def test_shard_seq_resumes_past_previous_run(self, tmp_path):
        """A restarted joiner must never os.replace-clobber shards a
        lagging online trainer has not consumed yet — numbering resumes
        after BOTH unconsumed (.libsvm) and consumed (.done) shards."""
        _, j = self._mk(tmp_path)
        for i in range(3):
            j.scored(_rec(f"r{i}", float(i), line=f"{i + 1}:1"))
            j.label(f"r{i}", 1, ts=5.0)
        assert self._shards(j) == ["shard-000000.libsvm"]
        # simulate the trainer consuming shard 0, then a serve restart
        os.replace(os.path.join(j.out_dir, "shard-000000.libsvm"),
                   os.path.join(j.out_dir, "shard-000000.libsvm.done"))
        _, j2 = self._mk(tmp_path)
        j2.scored(_rec("s0", 1.0, line="5:1"))
        j2.label("s0", 0, ts=2.0)
        j2.flush()
        assert self._shards(j2) == ["shard-000001.libsvm"]
        with open(os.path.join(j2.out_dir, "shard-000001.libsvm")) as f:
            assert f.read().splitlines() == ["0 5:1"]


# ---------------------------------------------------------------------------
# drift
# ---------------------------------------------------------------------------

class TestScoreDrift:
    def test_fires_on_shift_and_clears_when_stable(self):
        det = ScoreDriftDetector(block=100, threshold=0.2)
        det.observe(np.full(200, 0.45))   # two identical blocks: PSI ~ 0
        assert det.psi_last is not None and det.psi_last < 0.01
        assert not det.firing
        det.observe(np.full(100, 0.92))   # distribution jumps: fires
        assert det.firing and det.fired_total == 1
        det.observe(np.full(100, 0.92))   # stable at the NEW level: clears
        assert not det.firing and det.cleared_total == 1

    def test_gradual_noise_does_not_fire(self):
        rng = np.random.default_rng(0)
        det = ScoreDriftDetector(block=200, threshold=0.25)
        det.observe(rng.uniform(0.3, 0.7, size=1000))
        assert det.fired_total == 0

    def test_validation(self):
        with pytest.raises(ValueError):
            ScoreDriftDetector(block=0)
        with pytest.raises(ValueError):
            ScoreDriftDetector(threshold=0.0)


# ---------------------------------------------------------------------------
# online trainer (unit: pre-written shards, SGD servers)
# ---------------------------------------------------------------------------

def _libsvm(x):
    return " ".join(f"{i + 1}:{v:g}" for i, v in enumerate(x) if v)


def _make_rows(n, w_true, rng, *, min_margin=2.0):
    """Dense 0/1 rows with an unambiguous label under ``w_true``."""
    X, y = [], []
    while len(X) < n:
        x = np.zeros(len(w_true), np.float32)
        x[rng.choice(len(w_true), size=4, replace=False)] = 1.0
        m = float(x @ w_true)
        if abs(m) < min_margin:
            continue
        X.append(x)
        y.append(1 if m > 0 else 0)
    return np.stack(X), np.asarray(y, np.int32)


class TestOnlineTrainer:
    def test_consumes_shards_and_learns(self, tmp_path):
        rng = np.random.default_rng(0)
        w_true = np.where(np.arange(D) % 2 == 0, 1.0, -1.0).astype(np.float32)
        X, y = _make_rows(160, w_true, rng)
        shard_dir = tmp_path / "shards"
        shard_dir.mkdir()
        for s in range(4):
            with open(shard_dir / f"shard-{s:06d}.libsvm", "w") as f:
                for i in range(s * 40, (s + 1) * 40):
                    f.write(f"{y[i]} {_libsvm(X[i])}\n")
        cfg = Config(model="binary_lr", num_feature_dim=D, batch_size=20,
                     l2_c=0.0, sync_mode=False, learning_rate=0.5)
        with ServerGroup(1, 1, D, sync=False, learning_rate=0.5) as sg:
            tr = OnlineTrainer(cfg, sg.hosts, str(shard_dir),
                               accum_start=1, accum_growth=2.0,
                               accum_growth_every=2, accum_max=4,
                               poll_interval_s=0.05)
            stats = tr.run(max_shards=4)
            with KVWorker(sg.hosts, D) as kv:
                w = kv.pull()
            tr.close()
        assert stats["shards_consumed"] == 4
        assert stats["examples"] == 160
        assert stats["pushes"] >= 2
        # AdaBatch schedule grew (growth_every=2 pushes, x2, capped at 4)
        assert stats["accum_k"] > 1
        # consumed shards stepped aside
        assert not [p for p in os.listdir(shard_dir)
                    if p.endswith(".libsvm")]
        assert [p for p in os.listdir(shard_dir) if p.endswith(".done")]
        # and the model learned the separator
        acc = float((((X @ w) > 0).astype(np.int32) == y).mean())
        assert acc > 0.85, f"online trainer failed to learn (acc={acc})"

    def test_sparse_model_keyed_pushes(self, tmp_path):
        rng = np.random.default_rng(1)
        w_true = np.where(np.arange(D) % 2 == 0, 1.0, -1.0).astype(np.float32)
        X, y = _make_rows(120, w_true, rng)
        shard_dir = tmp_path / "shards"
        shard_dir.mkdir()
        with open(shard_dir / "shard-000000.libsvm", "w") as f:
            for i in range(len(y)):
                f.write(f"{y[i]} {_libsvm(X[i])}\n")
        cfg = Config(model="sparse_lr", num_feature_dim=D, batch_size=30,
                     l2_c=0.0, sync_mode=False, learning_rate=0.5)
        with ServerGroup(2, 1, D, sync=False, learning_rate=0.5) as sg:
            tr = OnlineTrainer(cfg, sg.hosts, str(shard_dir),
                               poll_interval_s=0.05)
            stats = tr.run(max_shards=1)
            with KVWorker(sg.hosts, D) as kv:
                w = kv.pull()
            tr.close()
        assert stats["examples"] == 120 and stats["pushes"] >= 1
        acc = float((((X @ w) > 0).astype(np.int32) == y).mean())
        assert acc > 0.8

    def test_rejects_unsupported_model(self, tmp_path):
        # blocked_lr stays rejected (ISSUE-10 satellite: the error now
        # NAMES why — raw-CTR hashing happens at shard ingest, so the
        # grouped row layout cannot be re-derived from feedback shards)
        cfg = Config(model="blocked_lr", num_feature_dim=D, block_size=8)
        with pytest.raises(ValueError, match="RAW categorical"):
            OnlineTrainer(cfg, "127.0.0.1:1", str(tmp_path))


# ---------------------------------------------------------------------------
# serve protocol (LABEL / ID lines, STATS, JSON ids)
# ---------------------------------------------------------------------------

class TestServeProtocol:
    def _server(self, tmp_path, with_feedback=True):
        from distlr_tpu.serve import ScoringEngine, ScoringServer  # noqa: PLC0415

        cfg = Config(model="binary_lr", num_feature_dim=D, l2_c=0.0)
        engine = ScoringEngine(cfg, max_batch_size=64)
        engine.set_weights(np.linspace(-1, 1, D).astype(np.float32))
        sink = None
        if with_feedback:
            sink = FeedbackSink(str(tmp_path / "spool"),
                                str(tmp_path / "shards"),
                                model="binary_lr", window_s=30.0,
                                shard_records=4)
        return ScoringServer(engine, feedback=sink), sink

    def test_id_and_label_lines(self, tmp_path):
        srv, sink = self._server(tmp_path)
        try:
            reply = srv.handle_line("ID req-1 3:1 5:1")
            assert not reply.startswith("ERR")
            assert len(sink.spool) == 1
            assert srv.handle_line("LABEL req-1 1") == "OK joined"
            assert srv.handle_line("LABEL req-1 0") == "OK duplicate"
            assert srv.handle_line("LABEL never-seen 1") == "OK pending"
            assert srv.handle_line("LABEL bad").startswith("ERR")
            assert srv.handle_line("LABEL x 7").startswith("ERR")
        finally:
            srv.stop()

    def test_label_without_sink_is_err(self, tmp_path):
        srv, _ = self._server(tmp_path, with_feedback=False)
        try:
            assert srv.handle_line("LABEL x 1").startswith("ERR")
            # plain scoring still works and nothing is journaled
            assert not srv.handle_line("3:1").startswith("ERR")
        finally:
            srv.stop()

    def test_json_ids_and_stats_schema(self, tmp_path):
        srv, sink = self._server(tmp_path)
        try:
            req = json.dumps({"rows": ["1:1", "2:1"], "ids": ["a", None]})
            doc = json.loads(srv.handle_line(req))
            assert len(doc["scores"]) == 2
            assert srv.handle_line("LABEL a 1") == "OK joined"
            # auto-id rows are spooled too (negative-sampling pool)
            assert len(sink.spool) == 1
            stats = srv.stats()
            assert "feedback" in stats
            assert stats["feedback"]["join"]["joined"] == 1
            bad = json.dumps({"rows": ["1:1"], "ids": ["a", "b"]})
            assert srv.handle_line(bad).startswith("ERR")
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# the closed loop
# ---------------------------------------------------------------------------

CHAOS_PLAN = {
    "seed": 11,
    "faults": [
        {"kind": "delay", "links": "*", "delay_ms": 3, "jitter_ms": 2},
        {"kind": "reset", "links": [0], "after_ops": 150},
    ],
}


class _LoopHarness:
    """serve → label → join → online trainer → live PS → hot reload."""

    def __init__(self, tmp_path, *, chaos=None, retry_attempts=0):
        from distlr_tpu.serve import (  # noqa: PLC0415
            HotReloader,
            LivePSWatcher,
            ScoringEngine,
            ScoringServer,
        )

        self.cfg = Config(model="binary_lr", num_feature_dim=D,
                          batch_size=24, l2_c=0.0, sync_mode=False,
                          ps_timeout_ms=20_000,
                          ps_retry_attempts=retry_attempts,
                          ps_retry_backoff_ms=20.0,
                          ps_retry_deadline_s=20.0)
        self.group = ServerGroup(
            1, 1, D, sync=False, optimizer="ftrl", ftrl_alpha=1.0,
            ftrl_beta=1.0, ftrl_l1=0.001, ftrl_l2=0.0, via_chaos=chaos,
        ).start()
        # the online trainer seeds the group (zero init) — the loop's
        # only trainer, exactly the from-cold production bring-up
        self.trainer = OnlineTrainer(
            self.cfg, self.group.hosts, str(tmp_path / "shards"),
            accum_start=1, accum_growth=2.0, accum_growth_every=50,
            accum_max=4, poll_interval_s=0.05, idle_flush_s=0.2)
        self.sink = FeedbackSink(
            str(tmp_path / "spool"), str(tmp_path / "shards"),
            model="binary_lr", window_s=1.0, negative_rate=0.3,
            shard_records=24, drift_block=120, drift_threshold=0.15,
            tick_interval_s=0.1, idle_flush_s=0.3)
        self.engine = ScoringEngine(self.cfg, max_batch_size=64)
        retry = None
        if retry_attempts:
            from distlr_tpu.ps import RetryPolicy  # noqa: PLC0415

            retry = RetryPolicy(attempts=retry_attempts, backoff_ms=20.0,
                                deadline_s=20.0)
        self.reloader = HotReloader(
            self.engine,
            LivePSWatcher(self.group.hosts, D, retry=retry),
            interval_s=0.1, jitter=0.0).start()
        self.reloader.wait_for_weights(timeout_s=20.0)
        self.server = ScoringServer(self.engine, feedback=self.sink,
                                    max_wait_ms=1.0,
                                    reloader=self.reloader).start()
        self._stop = threading.Event()
        self._trainer_thread = threading.Thread(
            target=self.trainer.run, kwargs={"stop": self._stop},
            daemon=True)
        self._trainer_thread.start()
        self._sock = socket.create_connection(
            (self.server.host, self.server.port), timeout=30.0)
        self._f = self._sock.makefile("rwb")
        self._next_id = 0

    def _exchange(self, line):
        self._f.write((line + "\n").encode())
        self._f.flush()
        reply = self._f.readline().decode().rstrip("\n")
        assert reply, "server closed mid-stream"
        return reply

    def drive(self, X, y, *, label_frac=0.85, rng=None):
        """Score + (mostly) label a traffic burst."""
        rng = rng or np.random.default_rng(0)
        for i in range(len(y)):
            rid = f"r{self._next_id}"
            self._next_id += 1
            reply = self._exchange(f"ID {rid} {_libsvm(X[i])}")
            assert not reply.startswith("ERR"), reply
            if rng.random() < label_frac:
                reply = self._exchange(f"LABEL {rid} {int(y[i])}")
                assert reply.startswith("OK"), reply

    def probe(self, X):
        req = json.dumps({"rows": [_libsvm(x) for x in X]})
        doc = json.loads(self._exchange(req))
        return np.asarray(doc["scores"], np.float64)

    def close(self):
        self._stop.set()
        self._trainer_thread.join(timeout=20)
        try:
            self._f.close()
            self._sock.close()
        except OSError:
            pass
        self.server.stop()
        self.trainer.close()
        self.group.stop()


def _run_closed_loop(tmp_path, *, chaos=None, retry_attempts=0,
                     deadline_s=60.0):
    rng = np.random.default_rng(42)
    w_true = np.where(np.arange(D) % 2 == 0, 1.0, -1.0).astype(np.float32)
    Xp, _ = _make_rows(8, w_true, rng)          # probes: 4 pos, 4 neg
    yp = (Xp @ w_true > 0).astype(np.int32)
    pos, neg = Xp[yp == 1], Xp[yp == 0]
    assert len(pos) and len(neg)

    h = _LoopHarness(tmp_path, chaos=chaos, retry_attempts=retry_attempts)
    try:
        def adapted(sign):
            sp, sn = h.probe(pos).mean(), h.probe(neg).mean()
            return (sp > 0.6 and sn < 0.4) if sign > 0 else \
                   (sp < 0.4 and sn > 0.6)

        def phase(truth_sign, tag):
            deadline = time.monotonic() + deadline_s
            while True:
                X, y = _make_rows(60, truth_sign * w_true, rng)
                h.drive(X, y, rng=rng)
                time.sleep(0.3)  # window ticks, trainer consumes, reloads
                if adapted(truth_sign):
                    return
                assert time.monotonic() < deadline, (
                    f"{tag}: served scores never tracked the label "
                    f"distribution; pos={h.probe(pos).mean():.3f} "
                    f"neg={h.probe(neg).mean():.3f} "
                    f"stats={h.sink.stats()} trainer={h.trainer.stats()}")

        # phase 1: learn the world from cold (scores start at 0.5)
        phase(+1, "phase1")
        # phase 2: THE FLIP — labels invert mid-run, zero restarts
        phase(-1, "phase2")
        assert h.sink.drift.fired_total >= 1, h.sink.drift.stats()
        # stable tail: consistent traffic until the drift alert clears
        deadline = time.monotonic() + deadline_s
        while h.sink.drift.firing:
            X, y = _make_rows(60, -w_true, rng)
            h.drive(X, y, rng=rng)
            time.sleep(0.2)
            assert time.monotonic() < deadline, (
                f"drift alert never cleared: {h.sink.drift.stats()}")
        # loop accounting: labels joined, never-labeled negative-sampled
        st = h.sink.stats()
        assert st["join"]["joined"] > 50, st
        assert h.trainer.pushes > 0 and h.trainer.examples > 0
        # the alert gauge is scrape-visible with its threshold label
        from distlr_tpu.obs.registry import get_registry  # noqa: PLC0415

        text = get_registry().prometheus_text()
        assert 'distlr_alert_score_drift{threshold="0.15"} 0' in text
        return h
    finally:
        h.close()


class TestClosedLoopEndToEnd:
    def test_closed_loop_tracks_label_flip(self, tmp_path):
        """Tier-1 acceptance: the full loop adapts to a mid-run label
        flip with zero restarts; drift fires then clears."""
        _run_closed_loop(tmp_path)

    @pytest.mark.slow
    def test_closed_loop_under_chaos(self, tmp_path):
        """Slow soak: same loop with the PS legs crossing the chaos
        proxy (delay + a mid-run reset) — faults cost retries, not
        restarts, and the loop still adapts."""
        from distlr_tpu.chaos import parse_plan  # noqa: PLC0415

        plan = parse_plan(CHAOS_PLAN)
        _run_closed_loop(tmp_path, chaos=plan, retry_attempts=4,
                         deadline_s=120.0)


# ---------------------------------------------------------------------------
# multi-worker shard claiming (ISSUE 7 satellite: the .claim protocol)
# ---------------------------------------------------------------------------

class TestMultiWorkerClaim:
    def _write_shards(self, shard_dir, n_shards, rows_per=20, seed=0):
        rng = np.random.default_rng(seed)
        w_true = np.where(np.arange(D) % 2 == 0, 1.0,
                          -1.0).astype(np.float32)
        X, y = _make_rows(n_shards * rows_per, w_true, rng)
        os.makedirs(shard_dir, exist_ok=True)
        for s in range(n_shards):
            with open(os.path.join(shard_dir, f"shard-{s:06d}.libsvm"),
                      "w") as f:
                for i in range(s * rows_per, (s + 1) * rows_per):
                    f.write(f"{y[i]} {_libsvm(X[i])}\n")

    def test_two_workers_consume_each_shard_exactly_once(self, tmp_path):
        """N `launch online` processes sharing one shard dir: the
        atomic `.claim` rename gives every shard exactly one owner —
        no shard trains twice, none is stranded."""
        shard_dir = str(tmp_path / "shards")
        self._write_shards(shard_dir, 8)
        cfg = Config(model="binary_lr", num_feature_dim=D, batch_size=20,
                     l2_c=0.0, sync_mode=False, learning_rate=0.5)
        with ServerGroup(1, 2, D, sync=False, learning_rate=0.5) as sg:
            trainers = [
                OnlineTrainer(cfg, sg.hosts, shard_dir, worker_id=i,
                              poll_interval_s=0.02)
                for i in range(2)
            ]
            stats = [None, None]

            def run(i):
                stats[i] = trainers[i].run(idle_exit_s=0.6)

            threads = [threading.Thread(target=run, args=(i,), daemon=True)
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
                assert not t.is_alive()
            for tr in trainers:
                tr.close()
        assert stats[0]["shards_consumed"] + stats[1]["shards_consumed"] == 8
        names = sorted(os.listdir(shard_dir))
        assert len([n for n in names if n.endswith(".done")]) == 8
        assert not [n for n in names if n.endswith((".libsvm", ".claim"))]
        # every example trained exactly once across the pair
        assert stats[0]["examples"] + stats[1]["examples"] == 8 * 20

    def test_claim_is_exclusive(self, tmp_path):
        shard_dir = str(tmp_path / "shards")
        self._write_shards(shard_dir, 1)
        cfg = Config(model="binary_lr", num_feature_dim=D, batch_size=20,
                     l2_c=0.0, sync_mode=False, learning_rate=0.5)
        path = os.path.join(shard_dir, "shard-000000.libsvm")
        with ServerGroup(1, 1, D, sync=False) as sg:
            tr = OnlineTrainer(cfg, sg.hosts, shard_dir,
                               poll_interval_s=0.02)
            claimed = tr._claim(path)
            assert claimed == path + ".claim"
            assert os.path.exists(claimed)
            # a raced second claim (same worker or a peer) loses cleanly
            assert tr._claim(path) is None
            tr.close()

    def test_stale_claim_reclaimed_and_consumed(self, tmp_path):
        """A worker that died mid-shard leaves a `.claim` nobody owns:
        after claim_stale_s it returns to the pool and a live worker
        finishes it."""
        shard_dir = str(tmp_path / "shards")
        self._write_shards(shard_dir, 1)
        path = os.path.join(shard_dir, "shard-000000.libsvm")
        orphan = path + ".claim"
        os.rename(path, orphan)
        old = time.time() - 3600.0
        os.utime(orphan, (old, old))  # the dead owner's claim time
        cfg = Config(model="binary_lr", num_feature_dim=D, batch_size=20,
                     l2_c=0.0, sync_mode=False, learning_rate=0.5)
        with ServerGroup(1, 1, D, sync=False) as sg:
            tr = OnlineTrainer(cfg, sg.hosts, shard_dir,
                               poll_interval_s=0.02, claim_stale_s=0.5)
            stats = tr.run(max_shards=1, idle_exit_s=10.0)
            tr.close()
        assert stats["shards_consumed"] == 1
        assert os.path.exists(path + ".done")
        assert not os.path.exists(orphan)

    def test_stale_claim_reclaimed_under_load(self, tmp_path):
        """Reclamation must not wait for an idle cycle: under sustained
        traffic `pending` never drains, but a dead peer's orphaned
        claim still re-pools on the next poll (regression: reclaim used
        to run only when the scan came back empty)."""
        shard_dir = str(tmp_path / "shards")
        self._write_shards(shard_dir, 2)
        path = os.path.join(shard_dir, "shard-000000.libsvm")
        orphan = path + ".claim"
        os.rename(path, orphan)
        old = time.time() - 3600.0
        os.utime(orphan, (old, old))
        cfg = Config(model="binary_lr", num_feature_dim=D, batch_size=20,
                     l2_c=0.0, sync_mode=False, learning_rate=0.5)
        with ServerGroup(1, 1, D, sync=False) as sg:
            tr = OnlineTrainer(cfg, sg.hosts, shard_dir,
                               poll_interval_s=0.02, claim_stale_s=0.5)
            # one shard consumed and out: with shard-000001 still
            # pending the loop never goes idle, yet the orphan must
            # already be back in the pool (or consumed as that shard)
            tr.run(max_shards=1, idle_exit_s=10.0)
            tr.close()
        assert not os.path.exists(orphan)

    def test_fresh_claim_not_reclaimed(self, tmp_path):
        """A claim younger than claim_stale_s belongs to a live peer —
        hands off."""
        shard_dir = str(tmp_path / "shards")
        self._write_shards(shard_dir, 1)
        path = os.path.join(shard_dir, "shard-000000.libsvm")
        os.rename(path, path + ".claim")  # fresh mtime = just claimed
        cfg = Config(model="binary_lr", num_feature_dim=D, batch_size=20,
                     l2_c=0.0, sync_mode=False, learning_rate=0.5)
        with ServerGroup(1, 1, D, sync=False) as sg:
            tr = OnlineTrainer(cfg, sg.hosts, shard_dir,
                               poll_interval_s=0.02, claim_stale_s=300.0)
            stats = tr.run(idle_exit_s=0.3)
            tr.close()
        assert stats["shards_consumed"] == 0
        assert os.path.exists(path + ".claim")

    def test_worker_id_validated(self, tmp_path):
        cfg = Config(model="binary_lr", num_feature_dim=D)
        with pytest.raises(ValueError, match="worker_id"):
            OnlineTrainer(cfg, "127.0.0.1:1", str(tmp_path), worker_id=-1)


# ---------------------------------------------------------------------------
# spool journal replay across a serve restart (ISSUE 8 satellite)
# ---------------------------------------------------------------------------

class TestSpoolReplay:
    def _sink(self, tmp_path, **kw):
        kw.setdefault("model", "binary_lr")
        kw.setdefault("window_s", 30.0)
        kw.setdefault("shard_records", 4)
        return FeedbackSink(str(tmp_path / "spool"), str(tmp_path / "shards"),
                            **kw)

    def _score_one(self, sink, rid, line="3:1 5:1"):
        sink.scored([line], (np.zeros((1, D), np.float32),),
                    np.array([0.5]), version=1, ids=[rid])

    def test_label_across_restart_joins(self, tmp_path):
        """The ROADMAP follow-on: pre-replay, a label arriving after a
        serve restart could only negative-sample — now it joins the
        journaled impression."""
        sink1 = self._sink(tmp_path)
        self._score_one(sink1, "survivor")
        sink1.stop()
        # "restart": a brand-new sink over the same directories
        sink2 = self._sink(tmp_path)
        assert sink2.spool.stats()["replayed"] == 1
        assert sink2.label("survivor", 1) == "joined"
        sink2.joiner.flush()
        shards = [n for n in os.listdir(tmp_path / "shards")
                  if n.endswith(".libsvm")]
        assert shards, "joined example never emitted"
        with open(tmp_path / "shards" / sorted(shards)[-1]) as f:
            assert f.read().splitlines()[-1].startswith("1 ")
        sink2.stop()

    def test_joined_requests_not_resurrected(self, tmp_path):
        """The join tombstone: a request joined BEFORE the restart must
        not re-join after it (double-counted click)."""
        sink1 = self._sink(tmp_path)
        self._score_one(sink1, "already-joined")
        assert sink1.label("already-joined", 1) == "joined"
        sink1.stop()
        sink2 = self._sink(tmp_path)
        assert sink2.spool.stats()["replayed"] == 0
        assert sink2.label("already-joined", 1) != "joined"
        sink2.stop()

    def test_expired_records_not_replayed(self, tmp_path):
        sink1 = self._sink(tmp_path, window_s=0.2)
        self._score_one(sink1, "too-old")
        sink1.stop()
        time.sleep(0.3)  # past the join window while "down"
        sink2 = self._sink(tmp_path, window_s=0.2)
        assert sink2.spool.stats()["replayed"] == 0
        assert sink2.label("too-old", 1) == "pending"
        sink2.stop()

    def test_replay_respects_capacity(self, tmp_path):
        sink1 = self._sink(tmp_path)
        for i in range(8):
            self._score_one(sink1, f"r{i}")
        sink1.stop()
        sink2 = self._sink(tmp_path, capacity=3)
        st = sink2.spool.stats()
        assert st["size"] == 3  # bounded, newest kept (FIFO eviction)
        assert sink2.label("r7", 1) == "joined"
        sink2.stop()

    def test_replay_carries_trace_context(self, tmp_path):
        """A label across a restart still continues the original
        request's distributed trace (the journal carries the ids)."""
        from distlr_tpu.obs import dtrace

        try:
            dtrace.configure(str(tmp_path / "run"), "serve", 0, sample=1.0)
            sink1 = self._sink(tmp_path)
            ctx = dtrace.new_trace()
            with dtrace.use(ctx):
                self._score_one(sink1, "traced")
            sink1.stop()
            sink2 = self._sink(tmp_path)
            rec = sink2.spool._records["traced"]
            assert rec.trace is not None
            assert rec.trace[0] == ctx.trace_id
            sink2.stop()
        finally:
            dtrace.reset_for_tests()
